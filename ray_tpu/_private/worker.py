"""The per-process runtime singleton and the public API implementation.

Role-equivalent to the reference's ``python/ray/_private/worker.py`` plus the
CoreWorker it wraps: owns the memory store, assigns object IDs for puts and
task returns, resolves task arguments, and implements ``init / shutdown /
get / put / wait / kill / cancel``. Execution is delegated to a backend: the
in-process ``LocalBackend`` by default, or a multiprocess cluster backend.
"""

from __future__ import annotations

import atexit
import logging
import os
import threading
from typing import Any, Dict, Optional, Sequence

from ray_tpu import exceptions as exc
from ray_tpu._private import state as state_mod
from ray_tpu._private.ids import JobID, ObjectID, TaskID, WorkerID
from ray_tpu._private.local_backend import LocalBackend
from ray_tpu._private.memory_store import MemoryStore
from ray_tpu._private.task_spec import TaskSpec
from ray_tpu.object_ref import ObjectRef

logger = logging.getLogger(__name__)

_global_worker: Optional["Worker"] = None
_init_lock = threading.Lock()


class _TaskContext(threading.local):
    """Per-thread stack of executing tasks (nested via reentrant get)."""

    def _stack(self):
        if not hasattr(self, "stack"):
            self.stack = []
        return self.stack

    def push(self, **kw):
        self._stack().append(kw)

    def pop(self):
        self._stack().pop()

    def current(self) -> Optional[dict]:
        s = self._stack()
        return s[-1] if s else None


class Worker:
    """The runtime embedded in the driver (and, conceptually, each worker)."""

    # Compact queued submissions (QueuedTaskHeader) are accepted by the
    # in-process backends; the thin ray-client proxy is not marked, so
    # remote() keeps building full specs there (the client wire contract
    # ships TaskSpec).
    supports_compact_submit = True

    def __init__(self, resources: Dict[str, float], namespace: Optional[str] = None):
        self.worker_id = WorkerID.from_random()
        self.job_id = JobID.from_random()
        self.namespace = namespace or f"ns-{self.job_id.hex()}"
        self.memory_store = MemoryStore()
        # Disk spilling under memory pressure (reference:
        # local_object_manager.h:41 + external_storage.py). The manager
        # object is cheap; its spill directory is only created on the
        # first actual spill. Budget/thresholds live in the config table.
        from ray_tpu._private.spilling import SpillManager

        self.memory_store.spill_manager = SpillManager(self.memory_store)
        self.task_context = _TaskContext()
        from ray_tpu._private.task_events import TaskEventBuffer

        self.task_events = TaskEventBuffer()
        self._put_counter_lock = threading.Lock()
        self._put_counters: dict[bytes, int] = {}
        self._driver_task_id = TaskID.from_random()
        # Set by SharedPlane.install in cluster mode: large values are
        # published to the node's shm segment for zero-copy cross-process
        # reads (plasma-provider role).
        self.shm_plane = None
        self.backend = LocalBackend(self, resources)
        # Named actors / placement groups / KV — the "GCS" of this runtime.
        self.gcs = state_mod.GlobalState(self)

    # ------------------------------------------------------------------
    # Object plumbing
    # ------------------------------------------------------------------

    def current_task_id(self) -> TaskID:
        ctx = self.task_context.current()
        if ctx is not None:
            return ctx["task_spec"].task_id
        return self._driver_task_id

    def next_put_id(self) -> ObjectID:
        task_id = self.current_task_id()
        with self._put_counter_lock:
            idx = self._put_counters.get(task_id.binary(), 0) + 1
            self._put_counters[task_id.binary()] = idx
        return ObjectID.for_put(task_id, idx)

    def put_object(self, value: Any) -> ObjectRef:
        if isinstance(value, ObjectRef):
            raise TypeError(
                "Calling put() on an ObjectRef is not allowed; pass the ref directly."
            )
        from ray_tpu._private.task_spec import job_id_for_submit

        ctx = self.task_context.current()
        oid = self.next_put_id()
        self.memory_store.put(
            oid, value,
            job_id=job_id_for_submit(ctx["task_spec"] if ctx else None))
        if self.shm_plane is not None:
            from ray_tpu._private.shm_plane import share_value

            share_value(self, oid, value)
        return ObjectRef(oid)

    def get_objects(self, refs: Sequence[ObjectRef], timeout: Optional[float] = None):
        self.backend.notify_blocked()
        try:
            return self.memory_store.get_many([r.id for r in refs], timeout)
        except exc.TaskError as e:
            raise e.as_instanceof_cause() from None
        finally:
            self.backend.notify_unblocked()

    def wait(self, refs, num_returns, timeout, fetch_local=True):
        self.backend.notify_blocked()
        try:
            ready_ids, _ = self.memory_store.wait(
                [r.id for r in refs], num_returns, timeout
            )
        finally:
            self.backend.notify_unblocked()
        # Two-pointer merge: the store returns ready ids as an ordered
        # subsequence of the input, so refs partition in one pass (a
        # by-id dict rebuilt per call was measurable at 1k-ref scale).
        ready, not_ready = [], []
        pos, n_ready = 0, len(ready_ids)
        for ref in refs:
            if pos < n_ready and ref.id == ready_ids[pos]:
                ready.append(ref)
                pos += 1
            else:
                not_ready.append(ref)
        return ready, not_ready

    # ------------------------------------------------------------------
    # Task plumbing (called by the backend)
    # ------------------------------------------------------------------

    def resolve_args(self, spec: TaskSpec):
        """Replace top-level ObjectRefs in args/kwargs with their values.

        Nested refs (inside containers) are passed through as refs —
        borrowing semantics, matching the reference.
        """

        def _resolve(v):
            if isinstance(v, ObjectRef):
                return self.memory_store.get(v.id)
            return v

        args = tuple(_resolve(a) for a in spec.args)
        kwargs = {k: _resolve(v) for k, v in spec.kwargs.items()}
        return args, kwargs

    def store_task_outputs(self, spec: TaskSpec, values, error=None):
        job = getattr(spec, "job_id", "") or ""
        if error is not None:
            for oid in spec.return_ids:
                self.memory_store.put(oid, None, error=error, job_id=job)
            return
        for oid, value in zip(spec.return_ids, values):
            self.memory_store.put(oid, value, job_id=job)
            if self.shm_plane is not None:
                # Default large-object path: serialize once into the
                # node segment and swap the heap entry to the zero-copy
                # view — the output lives in the (spillable) arena, not
                # heap+arena.
                from ray_tpu._private.shm_plane import publish_task_output

                publish_task_output(self, oid, value)

    def submit(self, spec: TaskSpec) -> list[ObjectRef]:
        refs = [ObjectRef(oid) for oid in spec.assign_return_ids()]
        self.backend.submit(spec)
        return refs

    # -- local handle refcounting ---------------------------------------

    def register_object_ref(self, ref: ObjectRef) -> int:
        return self.memory_store.add_local_ref(ref.id)

    def unregister_object_ref(self, oid: ObjectID) -> bool:
        return self.memory_store.remove_local_ref(oid)

    def shutdown(self):
        # Cluster-driver plumbing first (fetch dispatcher + release
        # batcher, installed by ClusterDriverMixin): both block on
        # their own wakeups and must be told the worker is going away.
        stop_plumbing = getattr(self, "stop_cluster_plumbing", None)
        if stop_plumbing is not None:
            stop_plumbing()
        self.backend.shutdown()
        # Drain deferred durable writes before the process lets go of
        # the store (group-commit makes the window between accept and
        # commit a few ms; shutdown is a durability boundary).
        close = getattr(self.gcs, "close_storage", None)
        if close is not None:
            close()
        manager = self.memory_store.spill_manager
        if manager is not None:
            manager.storage.destroy()


# ----------------------------------------------------------------------
# Module-level API (exported via ray_tpu/__init__.py)
# ----------------------------------------------------------------------


def global_worker() -> Worker:
    if _global_worker is None:
        # Auto-init may race with another thread's first API call; the lock
        # inside init() makes the loser reuse the winner's worker.
        init(ignore_reinit_error=True)
    return _global_worker


def global_worker_or_none() -> Optional[Worker]:
    return _global_worker


def is_initialized() -> bool:
    return _global_worker is not None


def init(
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    namespace: Optional[str] = None,
    object_store_memory: Optional[int] = None,
    ignore_reinit_error: bool = False,
    address: Optional[Any] = None,
    _system_config: Optional[Dict[str, Any]] = None,
    **kwargs,
) -> "Worker":
    """Start (or connect to) the runtime.

    Reference: ``ray.init`` (``python/ray/_private/worker.py:1096``). Here a
    single-node in-process runtime is brought up; multiprocess/cluster modes
    attach through ``ray_tpu.cluster_utils``. ``address="host:port"``
    connects as a thin client to a driver running a client server
    (`ray_tpu.enable_client_server` — the reference's ray:// client
    mode): the core API proxies there instead of running locally.
    """
    global _global_worker
    with _init_lock:
        if _global_worker is not None:
            if ignore_reinit_error:
                return _global_worker
            raise RuntimeError(
                "ray_tpu.init() called twice; pass ignore_reinit_error=True "
                "or call ray_tpu.shutdown() first."
            )
        if address is not None:
            from ray_tpu._private.ray_client import ClientWorker

            if address == "auto":
                address = os.environ.get("RAY_TPU_ADDRESS")
                if not address:
                    raise ValueError(
                        'init(address="auto") requires RAY_TPU_ADDRESS='
                        '"host:port" in the environment')
            if isinstance(address, str):
                host, _, port = address.rpartition(":")
                address = (host or "127.0.0.1", int(port))
            _global_worker = ClientWorker(tuple(address))
            atexit.register(shutdown)
            return _global_worker
        from ray_tpu._private.config import apply_system_config

        apply_system_config(_system_config)
        total: Dict[str, float] = {"CPU": float(num_cpus if num_cpus is not None
                                                else os.cpu_count() or 1)}
        try:
            import jax

            tpus = sum(1 for d in jax.devices() if d.platform == "tpu")
        except Exception:  # pragma: no cover - jax missing/broken
            tpus = 0
        total["TPU"] = float(num_tpus) if num_tpus is not None else float(tpus)
        if object_store_memory:
            total["object_store_memory"] = float(object_store_memory)
        total.update(resources or {})
        total = {k: v for k, v in total.items() if v > 0 or k == "CPU"}
        _global_worker = Worker(total, namespace=namespace)
        atexit.register(shutdown)
        return _global_worker


def shutdown():
    global _global_worker
    with _init_lock:
        if _global_worker is not None:
            _global_worker.shutdown()
            _global_worker = None


def get(refs, *, timeout: Optional[float] = None):
    w = global_worker()
    if isinstance(refs, ObjectRef):
        return w.get_objects([refs], timeout)[0]
    if isinstance(refs, list):
        for r in refs:
            if not isinstance(r, ObjectRef):
                raise TypeError(f"get() expects ObjectRef(s), got {type(r).__name__}")
        return w.get_objects(refs, timeout)
    raise TypeError(f"get() expects an ObjectRef or list, got {type(refs).__name__}")


def put(value) -> ObjectRef:
    return global_worker().put_object(value)


def wait(refs, *, num_returns: int = 1, timeout: Optional[float] = None,
         fetch_local: bool = True):
    if not isinstance(refs, list) or not all(isinstance(r, ObjectRef) for r in refs):
        raise TypeError("wait() expects a list of ObjectRefs")
    if len(set(refs)) != len(refs):
        raise ValueError("wait() got duplicate ObjectRefs")
    if num_returns <= 0 or num_returns > len(refs):
        raise ValueError(
            f"num_returns must be in [1, {len(refs)}], got {num_returns}"
        )
    return global_worker().wait(refs, num_returns, timeout, fetch_local)


def kill(actor_handle, *, no_restart: bool = True):
    from ray_tpu.actor import ActorHandle

    if not isinstance(actor_handle, ActorHandle):
        raise TypeError("kill() expects an ActorHandle")
    w = global_worker()
    w.gcs.remove_named_actor_by_id(actor_handle._actor_id)
    w.backend.kill_actor(actor_handle._actor_id, no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    global_worker().backend.cancel(ref.task_id())
