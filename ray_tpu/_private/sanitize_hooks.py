"""Sanitizer/scheduler instrumentation seams.

The runtime sanitizers (``tools/raysan``) and the deterministic
interleaving harness (``tools.raysan.sched``) need hooks *inside* the
concurrency-critical paths — the router's reserved→in-flight handoff,
the coalescing batcher's drain, the pipelined client's reader loop —
but ``ray_tpu`` must not import ``tools`` (the dependency points the
other way: tooling observes the runtime). This module is the seam:
near-zero-cost no-ops by default, installed into by raysan when a
sanitizer or schedule is active.

Cost when nothing is installed: one global load and a ``None`` check
per site. The sites are control-plane boundaries (a dispatch, a frame
flush, a teardown) — not per-object hot loops — so this stays far
below measurement noise; the A/B observability bench budget covers it.

Two hooks:

- ``sched_point(name)``: a named yield point. A deterministic schedule
  (``tools.raysan.sched.Schedule``) installs a callable that can park
  the calling thread until the scripted/seeded interleaving lets it
  cross. Points are crossed on every call in instrumented builds, so
  names must be stable identifiers (``"router.handoff"``, not
  per-request strings).
- ``ambient_set(kind, value)``: observation tap fired by the
  thread-local ambient setters in ``task_spec`` so the ambient
  sanitizer can see per-thread residue it cannot otherwise reach
  (C ``_thread._local`` storage is invisible from other threads).
  The calling thread's ident is derived here and handed to the
  installed observer as ``(kind, ident, value)``.
"""

from __future__ import annotations

from typing import Callable, Optional

_sched_point: Optional[Callable[[str], None]] = None
_ambient_set: Optional[Callable[[str, int, object], None]] = None


def sched_point(name: str) -> None:
    """Cross the named yield point (no-op unless a schedule is
    installed; see module docstring for cost)."""
    hook = _sched_point
    if hook is not None:
        hook(name)


def install_sched_point(fn: Optional[Callable[[str], None]]) -> None:
    global _sched_point
    _sched_point = fn


def ambient_set(kind: str, value: object) -> None:
    """Report an ambient thread-local write to the installed observer
    (called by ``task_spec.set_ambient_*`` with the NEW value)."""
    hook = _ambient_set
    if hook is not None:
        import threading

        hook(kind, threading.get_ident(), value)


def install_ambient_observer(
        fn: Optional[Callable[[str, int, object], None]]) -> None:
    global _ambient_set
    _ambient_set = fn
