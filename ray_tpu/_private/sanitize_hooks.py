"""Sanitizer/scheduler instrumentation seams.

The runtime sanitizers (``tools/raysan``) and the deterministic
interleaving harness (``tools.raysan.sched``) need hooks *inside* the
concurrency-critical paths — the router's reserved→in-flight handoff,
the coalescing batcher's drain, the pipelined client's reader loop —
but ``ray_tpu`` must not import ``tools`` (the dependency points the
other way: tooling observes the runtime). This module is the seam:
near-zero-cost no-ops by default, installed into by raysan when a
sanitizer or schedule is active.

Cost when nothing is installed: one global load and a ``None`` check
per site. The sites are control-plane boundaries (a dispatch, a frame
flush, a teardown) — not per-object hot loops — so this stays far
below measurement noise; the A/B observability bench budget covers it.

Four hooks:

- ``sched_point(name)``: a named yield point. A deterministic schedule
  (``tools.raysan.sched.Schedule``) installs a callable that can park
  the calling thread until the scripted/seeded interleaving lets it
  cross. Points are crossed on every call in instrumented builds, so
  names must be stable identifiers (``"router.handoff"``, not
  per-request strings).
- ``crash_point(name)``: a named crash-fault point at a protocol
  boundary (the group-commit window, a frame dispatch). The bounded
  model checker (``tools.raymc``) or a replay schedule may install a
  hook that raises :class:`SimulatedCrash` here, modelling a process
  dying at exactly this instant; the checking harness catches it at
  the top of the faulted activity and performs the kill/restart. A
  crash point doubles as a yield point for interleaving control.
- ``ambient_set(kind, value)``: observation tap fired by the
  thread-local ambient setters in ``task_spec`` so the ambient
  sanitizer can see per-thread residue it cannot otherwise reach
  (C ``_thread._local`` storage is invisible from other threads).
  The calling thread's ident is derived here and handed to the
  installed observer as ``(kind, ident, value)``.
- ``spec_op(name, phase, obj, payload)``: an operation-boundary tap on
  the pure decision cores (``QuotaLedger``, ``FairTaskQueue``,
  ``DepTable``, ``ActorRestartGate``, ``ShardedTable``) and the
  actor-call exactly-once protocol. ``tools/rayspec`` installs a
  history recorder here and checks the captured concurrent
  invocation/response histories against each core's executable
  sequential specification (linearizability / refinement). ``phase``
  is ``"call"`` (operation entered; ``payload`` = argument view) or
  ``"ret"`` (operation returning; ``payload`` = result view); ``obj``
  is the core instance, used only for identity so one process-wide
  recorder can partition events per core instance. Point names are
  ``spec.<core>.<op>``, registered in :data:`SPEC_POINTS` (folded into
  ``SCHED_POINTS`` so the R8 literal-name contract and the raymc point
  catalog cover them); while a recorder is installed, the ``call``
  phase also crosses the sched-point seam, so a raysan ``Schedule``
  can gate spec operations — that is how rayspec's emitted violation
  scripts replay.

Every product call site must use a literal name registered below in
``SCHED_POINTS``/``CRASH_POINTS`` (raylint R8 enforces it): a typo'd
name would silently never gate, and the registry IS the raymc point
catalog — the checker's map of where it can seize control.
"""

from __future__ import annotations

from typing import Callable, Optional


class SimulatedCrash(BaseException):
    """An injected crash fault: the process/component notionally dies at
    the crash point that raised this. A ``BaseException`` deliberately:
    product recovery code that catches ``Exception`` (or routes
    ``BaseException`` into an error *reply*) must not convert a
    simulated death into a handled error — the fault harness alone
    catches this, at the boundary of the activity it chose to kill."""

    def __init__(self, point: str):
        super().__init__(f"simulated crash at {point!r}")
        self.point = point


# Decision-core operation boundaries tapped by the rayspec history
# recorder (``spec.<core>.<op>``; crossed via :func:`spec_op`, not
# :func:`sched_point`). Registered separately so tooling can tell the
# two seam kinds apart, but folded into ``SCHED_POINTS`` below: R8's
# literal-name contract and raymc's point catalog cover both, and a
# raysan ``Schedule`` may gate a spec op's call phase while a recorder
# is installed (rayspec's violation scripts rely on it). raylint R9
# additionally pins the registry ↔ call-site ↔ SPEC_CATALOG agreement.
SPEC_POINTS = frozenset({
    # tenancy.QuotaLedger: queued-ceiling admission, queue exit, CPU
    # charge/release, the drainer's batched charge, lease slots
    "spec.quota.admit",
    "spec.quota.dequeue",
    "spec.quota.charge",
    "spec.quota.release",
    "spec.quota.drain",
    "spec.quota.lease_acquire",
    "spec.quota.lease_release",
    # sched_state.DepTable: park / ready-claim / sweep-claim
    "spec.dep.park",
    "spec.dep.ready",
    "spec.dep.sweep",
    # sched_state.ShardedTable: refinement of one flat dict
    "spec.table.get",
    "spec.table.set",
    "spec.table.pop",
    "spec.table.contains",
    "spec.table.setdefault",
    # actor_gate.ActorRestartGate: FSM edges + per-call decisions
    "spec.actor.register",
    "spec.actor.restart",
    "spec.actor.ready",
    "spec.actor.rollback",
    "spec.actor.dead",
    "spec.actor.route",
    "spec.actor.replay",
    # cluster head actor-call exactly-once protocol: a call entering
    # the in-flight table / its output REPORT being applied (the FT
    # gap (a) double-execution witness rides these)
    "spec.call.invoke",
    "spec.call.apply",
    # scheduler WFQ runnable queue: enqueue / fair pick
    "spec.wfq.put",
    "spec.wfq.pop",
    # kv_cache.PrefixCache: prefix-tree read (longest-match pin), extra
    # pin, unpin, block admission (may evict LRU), pressure eviction
    "spec.kv.lookup",
    "spec.kv.pin",
    "spec.kv.release",
    "spec.kv.admit",
    "spec.kv.evict",
})

# The registered yield-point catalog. Grouped by component; the first
# dotted segment is the point's conflict domain (raymc's partial-order
# reduction treats crossings in different domains as independent).
SCHED_POINTS = SPEC_POINTS | frozenset({
    # serve router: the reserved→in-flight slot handoff
    "router.handoff",
    # memory store: object publication and wait-path snapshot
    "store.put",
    "store.wait",
    # rpc batcher / pipelined channel / server dispatch
    "rpc.batcher.add",
    "rpc.batcher.flush",
    "rpc.pipeline.send",
    "rpc.pipeline.reader_edge",
    "rpc.pipeline.reply_handled",
    "rpc.pipeline.closed_set",
    "rpc.server.dispatch",
    "rpc.server.reply",
    # worker pool execution edge
    "workerpool.run",
    # gcs registry writes (the group-commit frontend)
    "gcs.put",
    # serve long-poll membership channel
    "longpoll.listen",
    "longpoll.notify",
    "longpoll.client.loop",
    # serve replica-direct dispatch: the proxy-side slot claim, the
    # long-poll-fed membership commit, and the completion release —
    # the handoff seams of the proxy→replica fast path (raymc
    # replica_direct proves no acquire returns a replica whose removal
    # already committed, and that slot accounting stays exact).
    "serve.direct.acquire",
    "serve.direct.update",
    "serve.direct.release",
    # cluster node: one coalesced submit_batch frame dispatch
    "cluster.submit_batch",
    # object plane: spill pipeline (disk write done → entry flip) and
    # transparent restore; one native descriptor-pull about to start
    "spill.mark",
    "spill.restore",
    "objplane.pull",
    # actor fault tolerance: the restart gate's routing decision (park /
    # dispatch / reject), the replay-or-reject decision for a call whose
    # node died mid-flight, and the restart FSM edges
    "actor.route",
    "actor.replay",
    "actor.restart.begin",
    "actor.restart.ready",
    # lineage reconstruction: a locate miss deciding to reconstruct,
    # the re-execution resubmit, and a restore from a spilled copy
    "recon.request",
    "recon.resubmit",
    "recon.restore",
    # head registration surface (GCS-restart convergence: the report-
    # returns-False → re-register path)
    "head.node_report",
    "head.register",
    # multi-process head: the coordinator's key->shard routing decision
    # and a shard's row-table apply (the cross_shard raymc scenario's
    # interleaving surface; the per-shard commit boundary reuses the
    # gcs.commit.* crash points of the shard's own store)
    "headshard.route",
    "headshard.apply",
    # tenancy enforcement: quota check-and-charge / release and the
    # over-quota park (the quota_admission raymc scenario's
    # interleaving surface). Each fires ONLY for jobs with a
    # configured quota, so unquota'd hot paths cross nothing. The WFQ
    # queue's enqueue/serve edges are gated scenario-side
    # (mc.sync.wfq.*) — a product crossing there would fire on every
    # idle dispatch-loop poll and get the runtime's own dispatcher
    # adopted into the exploration.
    "tenancy.acquire",
    "tenancy.release",
    "tenancy.park",
    # scheduler dep-park table: the ready-path claim and the death
    # sweep's claim (the dep_sweep raymc scenario's interleaving
    # surface — exactly-once handoff between the two).
    "sched.dep_ready",
    "sched.dep_sweep",
    # LLM prefix/KV cache: the lookup-pin, payload release, block
    # admission, and pressure eviction edges (the kv_cache_reuse raymc
    # scenario's interleaving surface — a hit racing admit/evict must
    # never read freed KV bytes).
    "llm.kv.lookup",
    "llm.kv.release",
    "llm.kv.admit",
    "llm.kv.evict",
})

CRASH_POINTS = frozenset({
    # sqlite group commit: death before the fsync-bearing COMMIT (the
    # window's accepted-but-undurable writes must roll back) vs. death
    # after it but before the ack returns (they must survive).
    "gcs.commit.before",
    "gcs.commit.after",
    # spill pipeline: death with the disk copy written but the store
    # entry not yet flipped (the file is an orphan, the value must
    # still be served from memory — never lost, never double-freed).
    "spill.write.after",
})

POINTS = SCHED_POINTS | CRASH_POINTS

_sched_point: Optional[Callable[[str], None]] = None
_crash_point: Optional[Callable[[str], None]] = None
_ambient_set: Optional[Callable[[str, int, object], None]] = None
_spec_op: Optional[Callable[[str, str, object, object], None]] = None
# Public mirror of "_spec_op is installed": the inline guard hot tap
# sites read (one module-attr load + truth test, ~30ns uninstalled —
# cheaper than calling spec_op just to no-op, and public so call sites
# outside _private stay R3-clean). Kept in sync by install_spec_op.
spec_taps_active = False


def sched_point(name: str) -> None:
    """Cross the named yield point (no-op unless a schedule is
    installed; see module docstring for cost)."""
    hook = _sched_point
    if hook is not None:
        hook(name)


def install_sched_point(fn: Optional[Callable[[str], None]]) -> None:
    global _sched_point
    _sched_point = fn


def crash_point(name: str) -> None:
    """Cross the named crash-fault point. No-op unless a fault harness
    is installed; the installed hook may raise :class:`SimulatedCrash`
    to kill the calling activity at exactly this boundary."""
    hook = _crash_point
    if hook is not None:
        hook(name)


def install_crash_point(fn: Optional[Callable[[str], None]]) -> None:
    global _crash_point
    _crash_point = fn


def spec_op(name: str, phase: str, obj: object,
            payload: object = None) -> None:
    """Report a decision-core operation boundary to the installed
    rayspec recorder (no-op unless one is installed; cost then is one
    global load and a ``None`` check — same contract as
    :func:`sched_point`). ``phase`` is ``"call"`` or ``"ret"``; the
    payload is a cheap view of args/result the recorder's per-point
    adapters interpret. While a recorder is installed, the call phase
    also crosses the sched-point seam so a raysan ``Schedule`` can
    order spec operations (rayspec violation-script replay)."""
    hook = _spec_op
    if hook is None:
        return
    if phase == "call":
        gate = _sched_point
        if gate is not None:
            gate(name)
    hook(name, phase, obj, payload)


def install_spec_op(
        fn: Optional[Callable[[str, str, object, object], None]]) -> None:
    global _spec_op, spec_taps_active
    _spec_op = fn
    spec_taps_active = fn is not None


def spec_recording() -> bool:
    """True while a rayspec recorder is installed — the gate for call
    sites whose probe PAYLOAD is itself costly to build (they must pay
    nothing when nothing records)."""
    return _spec_op is not None


def ambient_set(kind: str, value: object) -> None:
    """Report an ambient thread-local write to the installed observer
    (called by ``task_spec.set_ambient_*`` with the NEW value)."""
    hook = _ambient_set
    if hook is not None:
        import threading

        hook(kind, threading.get_ident(), value)


def install_ambient_observer(
        fn: Optional[Callable[[str, int, object], None]]) -> None:
    global _ambient_set
    _ambient_set = fn
