"""In-process object store: the future/value table behind ObjectRefs.

Equivalent in role to the reference's CoreWorker memory store
(``src/ray/core_worker/store_provider/memory_store/memory_store.h``): it
holds resolved values (or errors) for object IDs owned by this process and
lets callers block or register callbacks on unresolved ones. Values are
stored as Python objects (zero-copy; jax/numpy arrays are immutable in
practice), with promotion to the shared-memory store handled a level up.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ray_tpu._private.ids import ObjectID
from ray_tpu.exceptions import GetTimeoutError, ObjectLostError


@dataclass
class _Entry:
    event: threading.Event = field(default_factory=threading.Event)
    value: Any = None
    error: Optional[BaseException] = None
    ready: bool = False
    callbacks: list = field(default_factory=list)
    # number of ObjectRef handles alive in this process (best-effort GC)
    local_refs: int = 0


class MemoryStore:
    def __init__(self):
        # RLock: ObjectRef.__del__ can fire from GC while this process holds
        # the lock (allocation inside _entry triggers collection), re-entering
        # remove_local_ref on the same thread.
        self._lock = threading.RLock()
        self._entries: dict[ObjectID, _Entry] = {}

    def _entry(self, object_id: ObjectID) -> _Entry:
        entry = self._entries.get(object_id)
        if entry is None:
            entry = _Entry()
            self._entries[object_id] = entry
        return entry

    def put(self, object_id: ObjectID, value: Any,
            error: Optional[BaseException] = None) -> None:
        with self._lock:
            entry = self._entry(object_id)
            if entry.ready:
                return  # immutable once written
            entry.value = value
            entry.error = error
            entry.ready = True
            callbacks = entry.callbacks
            entry.callbacks = []
        entry.event.set()
        for cb in callbacks:
            cb(object_id)

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            entry = self._entries.get(object_id)
            return entry is not None and entry.ready

    def on_ready(self, object_id: ObjectID, callback: Callable[[ObjectID], None]) -> None:
        """Invoke callback when object resolves (immediately if already done)."""
        with self._lock:
            entry = self._entry(object_id)
            if not entry.ready:
                entry.callbacks.append(callback)
                return
        callback(object_id)

    def get(self, object_id: ObjectID, timeout: Optional[float] = None) -> Any:
        """Block for and return the value; raises the stored error if any."""
        with self._lock:
            entry = self._entry(object_id)
        if not entry.event.wait(timeout):
            raise GetTimeoutError(
                f"get() timed out after {timeout}s waiting for {object_id}"
            )
        if entry.error is not None:
            raise entry.error
        return entry.value

    def peek(self, object_id: ObjectID):
        """Return (ready, value, error) without blocking."""
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None or not entry.ready:
                return False, None, None
            return True, entry.value, entry.error

    def wait(self, object_ids: list[ObjectID], num_returns: int,
             timeout: Optional[float]) -> tuple[list[ObjectID], list[ObjectID]]:
        """Block until ``num_returns`` of ``object_ids`` are ready.

        Returns (ready, not_ready) preserving input order, matching the
        semantics of ``ray.wait`` (reference ``_private/worker.py:2565``).
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        cond = threading.Condition()
        ready_set: set[ObjectID] = set()

        def _on_ready(oid: ObjectID):
            with cond:
                ready_set.add(oid)
                cond.notify_all()

        for oid in object_ids:
            self.on_ready(oid, _on_ready)

        with cond:
            while len(ready_set) < min(num_returns, len(object_ids)):
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                cond.wait(remaining)
            # At most num_returns ready refs are returned (ray.wait
            # contract); extras stay in not_ready even if resolved.
            ready = [oid for oid in object_ids if oid in ready_set]
            ready = ready[:num_returns]
        ready_out = set(ready)
        not_ready = [oid for oid in object_ids if oid not in ready_out]
        return ready, not_ready

    # -- local reference counting (process-lifetime GC) ------------------

    def add_local_ref(self, object_id: ObjectID) -> None:
        with self._lock:
            self._entry(object_id).local_refs += 1

    def remove_local_ref(self, object_id: ObjectID) -> None:
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None:
                return
            entry.local_refs -= 1
            if entry.local_refs <= 0 and entry.ready:
                del self._entries[object_id]

    def evict(self, object_ids: list[ObjectID]) -> None:
        """Drop local copies entirely (unlike `free`, which poisons the
        entry): a later get blocks until the object is re-fetched or
        reconstructed. Used by the cluster cache and spilling."""
        with self._lock:
            for oid in object_ids:
                self._entries.pop(oid, None)

    def free(self, object_ids: list[ObjectID]) -> None:
        with self._lock:
            for oid in object_ids:
                entry = self._entries.get(oid)
                if entry is not None and entry.ready:
                    entry.value = None
                    entry.error = ObjectLostError(oid.hex(), f"object {oid} was freed")

    def num_objects(self) -> int:
        with self._lock:
            return len(self._entries)
