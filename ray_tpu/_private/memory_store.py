"""In-process object store: the future/value table behind ObjectRefs.

Equivalent in role to the reference's CoreWorker memory store
(``src/ray/core_worker/store_provider/memory_store/memory_store.h``): it
holds resolved values (or errors) for object IDs owned by this process and
lets callers block or register callbacks on unresolved ones. Values are
stored as Python objects (zero-copy; jax/numpy arrays are immutable in
practice), with promotion to the shared-memory store handled a level up.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ray_tpu._private import perf_stats as _perf_stats
from ray_tpu._private import sanitize_hooks
from ray_tpu._private.ids import ObjectID
from ray_tpu.exceptions import GetTimeoutError, ObjectLostError

# Wait-path observability: how often wait() resolves from the snapshot
# pass alone vs. parking on a _WaitGroup wake-up — the event-driven
# completion path PR 2 introduced (a wake-up storm here means waiters
# are subscribing faster than completions batch).
_WAIT_CALLS = _perf_stats.counter("wait_calls")
_WAIT_SNAPSHOT_HITS = _perf_stats.counter("wait_snapshot_hits")
_WAIT_WAKEUPS = _perf_stats.counter("wait_wakeups")


@dataclass
class _Entry:
    event: threading.Event = field(default_factory=threading.Event)
    value: Any = None
    error: Optional[BaseException] = None
    ready: bool = False
    callbacks: list = field(default_factory=list)
    # number of ObjectRef handles alive in this process (best-effort GC)
    local_refs: int = 0
    # primary-copy pin (cluster nodes pin task outputs until the head's
    # free — orthogonal to handle refs so borrow edge-detection stays
    # count==1/count==0)
    pinned: bool = False
    # spilling bookkeeping: estimated in-memory size; disk URL once the
    # value has been spilled (value is then None until restored)
    size: int = 0
    last_access: float = 0.0
    spilled_url: Optional[str] = None
    # value is a zero-copy view over the node's shm segment (the bytes
    # live in the arena, not this heap): excluded from the heap spill
    # budget and from heap spill candidacy — the SharedPlane owns its
    # lifecycle (pin released on entry drop, arena spill under
    # pressure).
    shm_backed: bool = False
    # the shm-backed value has been handed to an in-process reader
    # (get/peek/get_many) since the swap: such a reader may retain an
    # INNER array viewing the arena pages (invisible to a refcount
    # check on the container), so the entry is no longer arena-spill
    # eligible — its block must never be reused under a live view.
    shm_read: bool = False
    # Job/tenant tag of the task (or driver put) that produced this
    # object — the per-job object-store accounting key ("" = untagged).
    job_id: str = ""


class _WaitGroup:
    """One completion-event subscriber shared across a whole wait() call
    (the completion-event queue role of the reference's memory-store
    GetAsync path): entries signal it as they resolve, and it fires once
    the countdown hits zero. Replaces the old per-ref callback + shared
    condition scheme, whose cost was O(refs) lock/condvar round trips per
    wait even when every ref was already resolved."""

    __slots__ = ("event", "_needed", "_lock")

    def __init__(self, needed: int):
        self.event = threading.Event()
        self._needed = needed
        self._lock = threading.Lock()

    def on_ready(self, _object_id) -> None:
        with self._lock:
            self._needed -= 1
            if self._needed > 0:
                return
        self.event.set()


class MemoryStore:
    def __init__(self, spill_manager=None):
        # RLock: ObjectRef.__del__ can fire from GC while this process holds
        # the lock (allocation inside _entry triggers collection), re-entering
        # remove_local_ref on the same thread.
        self._lock = threading.RLock()
        self._entries: dict[ObjectID, _Entry] = {}
        # Optional SpillManager (ray_tpu._private.spilling): set by the
        # worker when an object-store budget is configured.
        self.spill_manager = spill_manager
        # Optional spill observer fn(object_id, url): cluster mode wires
        # this to the head's spill-URL directory so a lost object with a
        # surviving disk copy restores instead of re-executing. Called
        # OUTSIDE the store lock, best-effort.
        self.on_spilled = None

    def _entry(self, object_id: ObjectID) -> _Entry:
        entry = self._entries.get(object_id)
        if entry is None:
            entry = _Entry()
            self._entries[object_id] = entry
        return entry

    def put(self, object_id: ObjectID, value: Any,
            error: Optional[BaseException] = None,
            job_id: str = "", shm: bool = False) -> None:
        """``shm=True`` marks the value as a zero-copy view over the
        node segment (a shm/transfer fetch): its bytes are arena-
        resident, so it is excluded from the heap spill budget and the
        plane's pin (released on entry drop) owns its lifetime."""
        sanitize_hooks.sched_point("store.put")
        manager = self.spill_manager
        with self._lock:
            entry = self._entry(object_id)
            if entry.ready:
                return  # immutable once written
            entry.value = value
            entry.error = error
            entry.ready = True
            entry.shm_backed = shm and error is None
            if job_id:
                entry.job_id = job_id
            entry.last_access = time.monotonic()
            if manager is not None and error is None:
                from ray_tpu._private.spilling import estimate_size

                entry.size = estimate_size(value)
                if not entry.shm_backed:
                    manager.note_put(entry.size)
            callbacks = entry.callbacks
            entry.callbacks = []
        entry.event.set()
        for cb in callbacks:
            cb(object_id)
        if manager is not None and manager.over_threshold():
            manager.maybe_spill()

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            entry = self._entries.get(object_id)
            return entry is not None and entry.ready

    def job_object_stats(self) -> dict:
        """Per-job object accounting: job_id -> (objects, bytes) over
        resident entries (spilled values count — the job still owns
        them). Untagged entries roll up under ``""`` so the per-job
        rows always sum to the store's real footprint. Sizes are only
        estimated when a spill budget is configured; counts are always
        exact."""
        out: dict = {}
        with self._lock:
            for entry in self._entries.values():
                if not entry.ready:
                    continue
                n, b = out.get(entry.job_id, (0, 0))
                out[entry.job_id] = (n + 1, b + (entry.size or 0))
        return out

    def on_ready(self, object_id: ObjectID, callback: Callable[[ObjectID], None]) -> None:
        """Invoke callback when object resolves (immediately if already done)."""
        with self._lock:
            entry = self._entry(object_id)
            if not entry.ready:
                entry.callbacks.append(callback)
                return
        callback(object_id)

    def get(self, object_id: ObjectID, timeout: Optional[float] = None) -> Any:
        """Block for and return the value; raises the stored error if any."""
        with self._lock:
            entry = self._entry(object_id)
        if not entry.event.wait(timeout):
            raise GetTimeoutError(
                f"get() timed out after {timeout}s waiting for {object_id}"
            )
        # Snapshot value+url together under the lock: a concurrent
        # spiller setting value=None between two bare reads must not be
        # observable as a silent None result.
        with self._lock:
            error, value, url = entry.error, entry.value, entry.spilled_url
            entry.last_access = time.monotonic()
            if entry.shm_backed and value is not None:
                entry.shm_read = True
        if error is not None:
            raise error
        if url is not None and value is None:
            return self._restore(object_id, entry, url)
        return value

    def peek(self, object_id: ObjectID):
        """Return (ready, value, error) without blocking (except a
        transparent disk restore for spilled values)."""
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None or not entry.ready:
                return False, None, None
            error, value, url = entry.error, entry.value, entry.spilled_url
            entry.last_access = time.monotonic()
            if entry.shm_backed and value is not None:
                entry.shm_read = True
        if error is None and url is not None and value is None:
            return True, self._restore(object_id, entry, url), None
        return True, value, error

    def _restore(self, object_id: ObjectID, entry: _Entry, url: str):
        """Load a spilled value back (reference: restore IO worker path,
        `external_storage.py` restore_spilled_objects). Uses the caller's
        snapshotted url — a concurrent free()/evict() may clear the entry
        and delete the file, which must surface as the entry's error (or
        a typed loss), never a raw file error."""
        try:
            value = self.spill_manager.restore(url)
        except OSError:
            with self._lock:
                error = entry.error
            if error is not None:
                raise error
            raise ObjectLostError(
                object_id.hex(),
                f"spilled copy of {object_id} disappeared (released "
                f"concurrently?)")
        with self._lock:
            if entry.error is not None:
                raise entry.error
            if entry.value is None:
                entry.value = value
                entry.last_access = time.monotonic()
                self.spill_manager.note_put(entry.size)
            return entry.value

    def wait(self, object_ids: list[ObjectID], num_returns: int,
             timeout: Optional[float]) -> tuple[list[ObjectID], list[ObjectID]]:
        """Block until ``num_returns`` of ``object_ids`` are ready.

        Returns (ready, not_ready) preserving input order, matching the
        semantics of ``ray.wait`` (reference ``_private/worker.py:2565``).
        Event-driven: one lock pass snapshots what is already resolved;
        only unresolved entries get a (single, shared) completion
        subscriber, so a wait over N resolved refs costs one lock
        acquisition, not N callback registrations.
        """
        sanitize_hooks.sched_point("store.wait")
        target = min(num_returns, len(object_ids))
        group: Optional[_WaitGroup] = None
        entries = self._entries
        with self._lock:
            ready = []
            unresolved: list[ObjectID] = []
            for oid in object_ids:
                entry = entries.get(oid)
                if entry is not None and entry.ready:
                    ready.append(oid)
                else:
                    unresolved.append(oid)
            if len(ready) < target and (timeout is None or timeout > 0):
                group = _WaitGroup(target - len(ready))
                for oid in unresolved:
                    self._entry(oid).callbacks.append(group.on_ready)
        _WAIT_CALLS.inc()
        if group is None:
            _WAIT_SNAPSHOT_HITS.inc()
        else:
            group.event.wait(timeout)
            _WAIT_WAKEUPS.inc()
            # Re-snapshot: completions that raced the wakeup count.
            with self._lock:
                ready_set = {
                    oid for oid in object_ids
                    if (e := entries.get(oid)) is not None and e.ready
                }
            ready = [oid for oid in object_ids if oid in ready_set]
        # At most num_returns ready refs are returned (ray.wait
        # contract); extras stay in not_ready even if resolved.
        if len(ready) > num_returns:
            ready = ready[:num_returns]
        if not unresolved and len(ready) == len(object_ids):
            return ready, []
        ready_out = set(ready)
        not_ready = [oid for oid in object_ids if oid not in ready_out]
        return ready, not_ready

    def get_many(self, object_ids: list[ObjectID],
                 timeout: Optional[float] = None) -> list:
        """Values for every id, in order. One lock pass serves the
        already-resolved plain entries (the fan-out-get hot path:
        ``get([N refs])`` after completion was N lock+event round trips);
        pending, errored, or spilled entries fall back to the blocking
        per-object ``get`` under a shared deadline."""
        values = [None] * len(object_ids)
        slow: list[int] = []
        now = time.monotonic()
        with self._lock:
            for i, oid in enumerate(object_ids):
                entry = self._entries.get(oid)
                if entry is not None and entry.ready \
                        and entry.error is None \
                        and not (entry.spilled_url is not None
                                 and entry.value is None):
                    values[i] = entry.value
                    entry.last_access = now
                    if entry.shm_backed and entry.value is not None:
                        entry.shm_read = True
                else:
                    slow.append(i)
        if slow:
            deadline = None if timeout is None \
                else time.monotonic() + timeout
            for i in slow:
                remaining = None
                if deadline is not None:
                    remaining = max(0.0, deadline - time.monotonic())
                values[i] = self.get(object_ids[i], remaining)
        return values

    # -- shm-backed entries (SharedPlane swap/spill) ----------------------

    def swap_to_shm(self, object_id: ObjectID, shm_value: Any) -> bool:
        """Replace a resident heap value with its zero-copy shm view
        (the producer just published it into the arena): the heap copy
        is released and the entry's bytes stop counting against the
        heap spill budget. True when the entry is (now) shm-backed."""
        manager = self.spill_manager
        heap_size = 0
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None or not entry.ready or \
                    entry.error is not None:
                return False
            if entry.shm_backed:
                return True  # idempotent: already swapped
            if entry.value is None or entry.spilled_url is not None:
                return False
            entry.value = shm_value
            entry.shm_backed = True
            # Pre-swap readers got the HEAP value; view-retention
            # tracking restarts with the fresh shm view.
            entry.shm_read = False
            heap_size = entry.size
        if manager is not None and heap_size:
            manager.note_drop(heap_size)
        return True

    def entry_job(self, object_id: ObjectID) -> str:
        """Producing job of a ready entry ("" = untagged/unknown) — how
        the shared arena charges object bytes to tenants."""
        with self._lock:
            entry = self._entries.get(object_id)
            return "" if entry is None else entry.job_id

    def entry_size(self, object_id: ObjectID) -> int:
        """Estimated payload size of a ready entry (0 when unknown) —
        what object-location reports carry for locality scoring."""
        with self._lock:
            entry = self._entries.get(object_id)
            return 0 if entry is None else (entry.size or 0)

    def spill_shm_entry(self, object_id: ObjectID, plane) -> Optional[int]:
        """Spill a swapped (shm-backed) entry's payload to disk and
        flip the entry to URL-backed, so the caller (the plane's
        pressure sweep) can drop its pin and reclaim the arena block.
        Returns the payload size, or None when the entry is ineligible:
        not shm-backed, errored, or possibly still viewed by an
        in-process reader (whose zero-copy arrays would dangle if the
        arena block were reused) — any local read since the swap
        disqualifies it, since a reader may retain an INNER array the
        container's refcount cannot witness."""
        import sys

        manager = self.spill_manager
        if manager is None:
            return None
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None or not entry.ready or \
                    entry.error is not None or not entry.shm_backed \
                    or entry.value is None or entry.shm_read:
                return None
            # Belt over the read-tracking braces: entry.value slot +
            # getrefcount's argument temporary = 2; anything above
            # means someone holds the container right now.
            if sys.getrefcount(entry.value) > 2:
                return None
        payload = plane.payload_bytes(object_id.binary())
        if payload is None:
            return None
        url = manager.spill_payload(object_id, payload)
        sanitize_hooks.sched_point("spill.mark")
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None or not entry.ready or entry.value is None \
                    or not entry.shm_backed or entry.shm_read \
                    or sys.getrefcount(entry.value) > 2:
                stale = True
            else:
                entry.value = None
                entry.spilled_url = url
                entry.shm_backed = False
                stale = False
        if stale:
            manager.delete([url])
            return None
        self._notify_spilled(object_id, url)
        return len(payload)

    # -- spilling hooks (called by SpillManager) --------------------------

    def spill_candidates(self):
        """Cold→hot list of (oid, value, size, existing_url) eligible to
        spill: ready, no error, value resident, big enough."""
        from ray_tpu._private.config import ray_config

        with self._lock:
            out = [
                (e.last_access, oid, e.value, e.size, e.spilled_url)
                for oid, e in self._entries.items()
                if e.ready and e.error is None and e.value is not None
                and not e.shm_backed
                and e.size >= ray_config.min_spilling_size_bytes
            ]
        # last_access captured under the lock: entries may be deleted
        # concurrently, and the sort must not reach back into the dict.
        out.sort(key=lambda item: item[0])
        return [(oid, value, size, url)
                for _, oid, value, size, url in out]

    def mark_spilled(self, object_id: ObjectID, url: str) -> bool:
        """Drop the in-memory value, keeping the disk URL. Returns False
        if the entry disappeared (released meanwhile) — or became
        shm-backed (a publish swap raced the sweep's snapshot: the
        arena owns the bytes now, the heap sweep must not flip it)."""
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None or not entry.ready or entry.value is None \
                    or entry.shm_backed:
                return False
            entry.value = None
            entry.spilled_url = url
        self._notify_spilled(object_id, url)
        return True

    def _notify_spilled(self, object_id: ObjectID, url: str) -> None:
        hook = self.on_spilled
        if hook is not None:
            try:
                hook(object_id, url)
            except Exception:
                pass

    def _drop_entry_locked(self, entry: _Entry) -> Optional[str]:
        """Common release path: account the dropped bytes, hand back any
        spill URL for deletion."""
        manager = self.spill_manager
        if manager is not None and entry.ready and entry.error is None \
                and entry.value is not None and not entry.shm_backed:
            manager.note_drop(entry.size)
        return entry.spilled_url

    # -- local reference counting (process-lifetime GC) ------------------

    def add_local_ref(self, object_id: ObjectID) -> int:
        """Returns the new local handle count (1 = first handle)."""
        with self._lock:
            entry = self._entry(object_id)
            entry.local_refs += 1
            return entry.local_refs

    def local_ref_count(self, object_id: ObjectID) -> int:
        with self._lock:
            entry = self._entries.get(object_id)
            return 0 if entry is None else max(entry.local_refs, 0)

    def remove_local_ref(self, object_id: ObjectID) -> bool:
        """Returns True when this drop took the handle count to zero."""
        url = None
        zero = False
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None:
                return False
            entry.local_refs -= 1
            if entry.local_refs <= 0:
                zero = True
                if entry.ready and not entry.pinned:
                    url = self._drop_entry_locked(entry)
                    del self._entries[object_id]
        if url is not None and self.spill_manager is not None:
            self.spill_manager.delete([url])
        return zero

    def pin_object(self, object_id: ObjectID) -> None:
        """Keep the local copy regardless of handle count (plasma
        primary-copy role); released by `unpin_object` or `evict`."""
        with self._lock:
            self._entry(object_id).pinned = True

    def unpin_object(self, object_id: ObjectID) -> None:
        url = None
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None:
                return
            entry.pinned = False
            if entry.local_refs <= 0 and entry.ready:
                url = self._drop_entry_locked(entry)
                del self._entries[object_id]
        if url is not None and self.spill_manager is not None:
            self.spill_manager.delete([url])

    def evict(self, object_ids: list[ObjectID]) -> None:
        """Drop local copies entirely (unlike `free`, which poisons the
        entry): a later get blocks until the object is re-fetched or
        reconstructed. Used by the cluster cache and spilling."""
        urls = []
        with self._lock:
            for oid in object_ids:
                entry = self._entries.pop(oid, None)
                if entry is not None:
                    url = self._drop_entry_locked(entry)
                    if url is not None:
                        urls.append(url)
        if urls and self.spill_manager is not None:
            self.spill_manager.delete(urls)

    def free(self, object_ids: list[ObjectID]) -> None:
        urls = []
        with self._lock:
            for oid in object_ids:
                entry = self._entries.get(oid)
                if entry is not None and entry.ready:
                    url = self._drop_entry_locked(entry)
                    if url is not None:
                        urls.append(url)
                        entry.spilled_url = None
                    entry.value = None
                    entry.shm_backed = False
                    entry.error = ObjectLostError(oid.hex(), f"object {oid} was freed")
        if urls and self.spill_manager is not None:
            self.spill_manager.delete(urls)

    def num_objects(self) -> int:
        with self._lock:
            return len(self._entries)
