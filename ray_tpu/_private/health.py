"""Health + SLO plane: overload signals and node/cluster verdicts.

Role-equivalent to the reference's GCS health-check manager plus the
autoscaler/raylet overload heuristics, unified into one queryable
surface: the signals the scheduler, Serve router, and autoscaler need
to *react* to load — not just chart it — computed from state the
observability plane (PR 3) already collects.

Signals, all cheap and sampled on scrape (never on a hot path):

- **Serve SLO burn**: per-route multi-window burn rates computed from
  the cumulative ``serve_request_seconds`` fast-path distributions.
  ``burn = bad_fraction(window) / error_budget`` — 1.0 means the route
  is consuming its error budget exactly at the sustainable rate,
  above ``health_slo_burn_threshold`` means the SLO is actively
  burning down (the classic multi-window burn-rate alert shape).
- **Event-loop lag**: how late a timed callback fires on the Serve
  proxy / replica asyncio loops — the canonical single-threaded
  event-loop overload signal (``install_loop_lag_sampler``).
- **Scheduler queue depth**: ``LocalBackend.queue_depths()`` backlog.
- **Memory pressure**: the memory monitor's sampled usage fraction.

``evaluate_health`` produces the ``/api/healthz`` payload: this
process's verdict plus — on a cluster head — a per-node verdict read
from each node's shipped metrics snapshot, rolled up into one cluster
status whose ``reasons`` name the overloaded signal.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Optional, Tuple

from ray_tpu._private import perf_stats
from ray_tpu._private.config import ray_config

# -- event-loop lag ----------------------------------------------------------

_LAG_LOCK = threading.Lock()
# component -> (wall ts, lag_s): the LAST sample, so the health verdict
# recovers the moment the loop does (the cumulative distribution keeps
# the history for exposition, but its p95 never comes back down).
_LAST_LAG: Dict[str, Tuple[float, float]] = {}
# component -> install token: only the NEWEST sampler for a component
# may write. A replica redeploy leaves the old loop (and its sampler)
# running as an orphan daemon thread; without the token its idle ~0
# readings would last-write-wins mask the live replica's lag.
_SAMPLER_TOKENS: Dict[str, object] = {}


def note_loop_lag(component: str, lag_s: float) -> None:
    with _LAG_LOCK:
        _LAST_LAG[component] = (time.time(), lag_s)


def recent_loop_lag(max_age_s: float = 15.0) -> Dict[str, float]:
    """Freshest lag sample per component; stale components drop out
    (a stopped proxy must not pin a degraded verdict forever)."""
    now = time.time()
    with _LAG_LOCK:
        return {c: lag for c, (ts, lag) in _LAST_LAG.items()
                if now - ts <= max_age_s}


def install_loop_lag_sampler(loop, component: str):
    """Schedule a lag sampler on an asyncio loop (which may run in
    another thread). Each tick measures scheduling delay — actual wait
    minus requested sleep — and records it to the
    ``event_loop_lag_seconds{component=...}`` distribution plus the
    last-sample table the health verdict reads. Returns the
    concurrent.futures handle (the sampler dies with its loop), or
    None when sampling is disabled."""
    import asyncio

    period = ray_config.loop_lag_sample_period_s
    if period <= 0:
        return None
    stat = perf_stats.dist("event_loop_lag_seconds",
                           tags={"component": component},
                           bounds=perf_stats.LATENCY_BOUNDS)
    token = object()
    with _LAG_LOCK:
        _SAMPLER_TOKENS[component] = token

    async def sampler():
        while True:
            t0 = loop.time()
            await asyncio.sleep(period)
            lag = max(0.0, loop.time() - t0 - period)
            with _LAG_LOCK:
                if _SAMPLER_TOKENS.get(component) is not token:
                    return  # superseded by a newer loop's sampler
                _LAST_LAG[component] = (time.time(), lag)
            stat.record(lag)

    return asyncio.run_coroutine_threadsafe(sampler(), loop)


def remove_loop_lag_component(component: str) -> None:
    """Retire a component's sampler state at orderly teardown (stopped
    replica/proxy): drops it from the last-sample table immediately
    instead of aging out over ``max_age_s``, and frees its supersede
    token so the tables don't grow with every redeploy."""
    with _LAG_LOCK:
        _LAST_LAG.pop(component, None)
        _SAMPLER_TOKENS.pop(component, None)


# -- serve SLO burn ----------------------------------------------------------


def parse_slo_targets() -> Dict[str, Tuple[float, float]]:
    """``serve_slo_targets`` is ``"route=latency_s[:objective],..."``
    (e.g. ``"/chat=0.25:0.999,/embed=0.1"``); routes not listed fall
    back to ``serve_slo_default_latency_s`` /
    ``serve_slo_default_objective``. Malformed entries are skipped —
    a config typo must not take down the scrape path."""
    out: Dict[str, Tuple[float, float]] = {}
    for part in (ray_config.serve_slo_targets or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        route, _, rest = part.partition("=")
        bits = rest.split(":")
        try:
            lat = float(bits[0])
            obj = float(bits[1]) if len(bits) > 1 \
                else ray_config.serve_slo_default_objective
        except (ValueError, IndexError):
            continue
        out[route.strip()] = (lat, obj)
    return out


class SloTracker:
    """Multi-window burn rates from cumulative route latency counts.

    The fast-path ``serve_request_seconds`` dists only ever grow, so
    windowed rates need history: each ``sample()`` snapshots the
    per-route (total, over-target) cumulative counts, and
    ``burn_rates()`` diffs the newest snapshot against the newest one
    at least a window old. A young process reports over its lifetime
    (the oldest snapshot) rather than zero."""

    def __init__(self):
        self._lock = threading.Lock()
        self._samples: "deque[Tuple[float, Dict[str, Tuple[int, int]]]]" \
            = deque()

    def _cumulative(self) -> Dict[str, Tuple[int, int]]:
        """route -> (total requests, SLO-bad requests), summed across
        status tags. A request is good when it landed in a latency
        bucket whose upper bound is <= the target AND did not fail
        server-side: 5xx series — crucially including the proxy's own
        fast load-shed 503s — are bad at any latency, else a route
        rejecting most traffic would read as healthy precisely when
        the shedding it triggers should be driving the burn alert."""
        targets = parse_slo_targets()
        default_lat = ray_config.serve_slo_default_latency_s
        out: Dict[str, list] = {}
        for name, tags, stat in perf_stats.stats_items():
            if name != "serve_request_seconds" or \
                    not isinstance(stat, perf_stats.Dist):
                continue
            tagd = dict(tags)
            route = tagd.get("route", "(unmatched)")
            lat = targets.get(route, (default_lat, 0.0))[0]
            total = stat.total
            good = 0
            if not tagd.get("status", "").startswith("5"):
                for bound, c in zip(stat.bounds, stat.counts):
                    if bound > lat:
                        break
                    good += c
            cur = out.setdefault(route, [0, 0])
            cur[0] += total
            cur[1] += max(0, total - good)
        return {r: (t, b) for r, (t, b) in out.items()}

    def sample(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        cum = self._cumulative()
        horizon = ray_config.slo_burn_long_window_s * 1.5 + 1.0
        with self._lock:
            self._samples.append((now, cum))
            while self._samples and now - self._samples[0][0] > horizon:
                self._samples.popleft()

    def burn_rates(self, now: Optional[float] = None) \
            -> Dict[str, Dict[str, float]]:
        """{route: {"short": burn, "long": burn}} over the configured
        windows. burn = (over-target fraction in window) / (1 -
        objective); 0 when the route saw no traffic in the window."""
        now = time.time() if now is None else now
        with self._lock:
            samples = list(self._samples)
        if not samples:
            return {}
        latest = samples[-1][1]
        targets = parse_slo_targets()
        default_obj = ray_config.serve_slo_default_objective
        out: Dict[str, Dict[str, float]] = {}
        for wname, wlen in (
                ("short", ray_config.slo_burn_short_window_s),
                ("long", ray_config.slo_burn_long_window_s)):
            base = samples[0][1]
            for ts, cum in samples:
                if now - ts >= wlen:
                    base = cum
                else:
                    break
            for route, (total, bad) in latest.items():
                b_total, b_bad = base.get(route, (0, 0))
                d_total = total - b_total
                d_bad = bad - b_bad
                obj = targets.get(route, (0.0, default_obj))[1]
                budget = max(1e-9, 1.0 - obj)
                burn = (d_bad / d_total / budget) if d_total > 0 else 0.0
                out.setdefault(route, {})[wname] = burn
        return out

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()


tracker = SloTracker()


# -- degraded-component providers --------------------------------------------

# Process-wide registry of component health providers: subsystems that
# KNOW about dead/degraded components (the serve controller's replica
# supervision, the proxy-fleet supervisor) register a callable
# returning current reason strings, and /api/healthz folds them in —
# the dependency points downward (serve registers into health, health
# never imports serve; same contract as register_stats_provider).
_PROVIDER_LOCK = threading.Lock()
_DEGRADED_PROVIDERS: Dict[str, Any] = {}


def register_degraded_provider(key: str, fn) -> None:
    """Register (or replace) a component-health provider. ``fn()``
    returns a list of degraded-reason strings (empty = healthy); it is
    called on every healthz evaluation and must be cheap and
    non-blocking (read a dict under a lock, never RPC)."""
    with _PROVIDER_LOCK:
        _DEGRADED_PROVIDERS[key] = fn


def unregister_degraded_provider(key: str) -> None:
    with _PROVIDER_LOCK:
        _DEGRADED_PROVIDERS.pop(key, None)


_SECTION_PROVIDERS: Dict[str, Any] = {}


def register_section_provider(key: str, fn) -> None:
    """Register a STRUCTURED healthz section: ``fn()`` returns plain
    data that lands verbatim under ``key`` in the /api/healthz payload
    (e.g. the multi-process head's per-shard verdict list). Same
    contract as degraded providers: cheap, non-blocking, no RPC."""
    with _PROVIDER_LOCK:
        _SECTION_PROVIDERS[key] = fn


def unregister_section_provider(key: str) -> None:
    with _PROVIDER_LOCK:
        _SECTION_PROVIDERS.pop(key, None)


def provider_sections() -> Dict[str, Any]:
    """Current structured sections from every registered provider; a
    broken provider degrades to absent rather than failing healthz."""
    with _PROVIDER_LOCK:
        providers = dict(_SECTION_PROVIDERS)
    sections = {}
    for key, fn in providers.items():
        try:
            sections[key] = fn()
        except Exception:
            continue
    return sections


def provider_reasons() -> list:
    """Current reasons from every registered provider; a broken
    provider degrades to absent rather than failing the endpoint."""
    with _PROVIDER_LOCK:
        providers = list(_DEGRADED_PROVIDERS.values())
    reasons = []
    for fn in providers:
        try:
            reasons.extend(str(r) for r in fn() or ())
        except Exception:
            continue
    return reasons


def snapshot_state() -> dict:
    """Plain-data snapshot of this module's process-global state: the
    global tracker's burn-rate history plus the loop-lag sample/token
    tables. With :func:`restore_state` this is the reset-capable API
    tests use to guarantee one test's health recordings (a 5xx burst,
    an installed lag sampler) never read as live signal in the next —
    the structural fix for the order-dependent healthz flake."""
    with tracker._lock:
        samples = list(tracker._samples)
    with _LAG_LOCK:
        lag = dict(_LAST_LAG)
        tokens = dict(_SAMPLER_TOKENS)
    with _PROVIDER_LOCK:
        providers = dict(_DEGRADED_PROVIDERS)
    return {"tracker_samples": samples, "loop_lag": lag,
            "sampler_components": tokens,
            "degraded_providers": providers}


def restore_state(snapshot: dict) -> None:
    """Restore :func:`snapshot_state` state. Sampler tokens are
    restored too: a sampler installed during the restored-over window
    loses its token and retires itself at its next tick (the same
    supersede mechanism a redeploy uses)."""
    with tracker._lock:
        tracker._samples.clear()
        tracker._samples.extend(snapshot["tracker_samples"])
    with _LAG_LOCK:
        _LAST_LAG.clear()
        _LAST_LAG.update(snapshot["loop_lag"])
        _SAMPLER_TOKENS.clear()
        _SAMPLER_TOKENS.update(snapshot["sampler_components"])
    with _PROVIDER_LOCK:
        _DEGRADED_PROVIDERS.clear()
        _DEGRADED_PROVIDERS.update(
            snapshot.get("degraded_providers") or {})


# -- scrape-time collection --------------------------------------------------


def collect_health_metrics() -> None:
    """Fold health signals into the metrics registry (called by
    ``collect_runtime_metrics`` on every scrape/ship): SLO burn gauges,
    last event-loop lag per component, and memory pressure. Worker
    nodes thereby ship these in their metric snapshots, which is what
    lets the head compute per-node verdicts without extra RPCs."""
    from ray_tpu._private import worker as worker_mod
    from ray_tpu._private.memory_monitor import current_pressure
    from ray_tpu._private.runtime_metrics import _gauge, _set_series

    tracker.sample()
    for route, windows in tracker.burn_rates().items():
        for wname, burn in windows.items():
            _gauge("ray_tpu_serve_slo_burn_rate",
                   "Serve SLO error-budget burn multiple by route/window",
                   tag_keys=("route", "window")).set(
                burn, tags={"route": route, "window": wname})
    # Fresh-snapshot series: a component whose sampler died (stopped
    # proxy, retired replica) must read 0, not its last value — the
    # shipped gauge is what per-node healthz verdicts are computed
    # from, and a frozen above-threshold reading would pin the node
    # degraded forever.
    _set_series("ray_tpu_event_loop_lag_last_seconds",
                "Most recent event-loop scheduling-lag sample",
                "component", recent_loop_lag())
    _gauge("ray_tpu_memory_pressure",
           "Node memory usage fraction (cgroup v2 / meminfo)").set(
        current_pressure())
    # Scheduler-pressure gauges (LocalBackend.queue_depths): a worker
    # node's snapshot carries them to the head, which is where the
    # per-node healthz verdict reads them back out.
    w = worker_mod.global_worker_or_none()
    depths = None
    if w is not None:
        try:
            depths = w.backend.queue_depths()
        except Exception:
            depths = None
        if depths:
            _gauge("ray_tpu_sched_backlog",
                   "Tasks queued but not yet dispatched").set(
                float(depths.get("backlog", 0)))
            _gauge("ray_tpu_sched_parked_for_resources",
                   "Runnable tasks parked waiting for resources").set(
                float(depths.get("parked_for_resources", 0)))
            _gauge("ray_tpu_sched_waiting_for_deps",
                   "Tasks parked on unresolved dependencies").set(
                float(depths.get("waiting_for_deps", 0)))
    # Flight-recorder sample ring: the same signals, kept as bounded
    # history per process so a degradation-triggered dump can show the
    # minutes BEFORE the verdict flipped, not just the instant of it.
    from ray_tpu._private import flight_recorder

    flight_recorder.note_sample("health", {
        "memory_pressure": current_pressure(),
        "queue_depths": depths or {},
        "loop_lag": recent_loop_lag(),
        "slo_burn": {r: ws.get("short", 0.0)
                     for r, ws in tracker.burn_rates().items()},
    })


# -- verdicts ----------------------------------------------------------------


def _local_signals(worker) -> Dict[str, Any]:
    from ray_tpu._private.memory_monitor import current_pressure

    # Burn rates are diffs between SNAPSHOTS of the cumulative route
    # counts: take one now, so a healthz consumer gets live burn even
    # when nothing is scraping /api/metrics (the other sampling site).
    tracker.sample()
    sig: Dict[str, Any] = {
        "memory_pressure": current_pressure(),
        "sched_backlog": 0,
        "loop_lag": recent_loop_lag(),
        "slo_burn": {r: w.get("short", 0.0)
                     for r, w in tracker.burn_rates().items()},
    }
    try:
        backend = worker.backend
        lb = getattr(backend, "local_backend", backend)
        sig["sched_backlog"] = lb.queue_depths()["backlog"]
    except Exception:
        pass
    return sig


def evaluate_signals(sig: Dict[str, Any]) -> Dict[str, Any]:
    """One node's verdict from its signal dict; every reason names the
    overloaded signal first so operators (and the scheduler/router)
    can key off it."""
    reasons = []
    pressure = float(sig.get("memory_pressure") or 0.0)
    if pressure > ray_config.health_memory_pressure_threshold:
        reasons.append(
            f"memory_pressure: usage {pressure:.2f} above threshold "
            f"{ray_config.health_memory_pressure_threshold:.2f}")
    backlog = int(sig.get("sched_backlog") or 0)
    if backlog > ray_config.health_backlog_threshold:
        reasons.append(
            f"sched_backlog: {backlog} queued tasks above threshold "
            f"{ray_config.health_backlog_threshold}")
    for comp, lag in sorted((sig.get("loop_lag") or {}).items()):
        if lag > ray_config.health_loop_lag_threshold_s:
            reasons.append(
                f"event_loop_lag: {comp} loop {lag * 1e3:.0f}ms behind "
                f"(threshold "
                f"{ray_config.health_loop_lag_threshold_s * 1e3:.0f}ms)")
    for route, burn in sorted((sig.get("slo_burn") or {}).items()):
        if burn > ray_config.health_slo_burn_threshold:
            reasons.append(
                f"slo_burn: route {route} consuming error budget at "
                f"{burn:.1f}x (threshold "
                f"{ray_config.health_slo_burn_threshold:.1f}x)")
    return {"status": "degraded" if reasons else "ok",
            "reasons": reasons, "signals": sig}


def _signals_from_snapshot(snap: dict) -> Dict[str, Any]:
    """Health signals out of a node's shipped metrics-registry snapshot
    (the gauges collect_health_metrics set on that node)."""

    def gauge_value(name: str, default: float = 0.0) -> float:
        series = (snap.get(name) or {}).get("series") or []
        return float(series[0][1]) if series else default

    def tagged(name: str, key: str) -> Dict[str, float]:
        out = {}
        for tag_pairs, v in (snap.get(name) or {}).get("series") or []:
            tags = {k: val for k, val in tag_pairs}
            out[tags.get(key, "")] = float(v)
        return out

    slo = {}
    for tag_pairs, v in (snap.get("ray_tpu_serve_slo_burn_rate")
                         or {}).get("series") or []:
        tags = {k: val for k, val in tag_pairs}
        if tags.get("window") == "short":
            slo[tags.get("route", "")] = float(v)
    return {
        "memory_pressure": gauge_value("ray_tpu_memory_pressure"),
        "sched_backlog": gauge_value("ray_tpu_sched_backlog"),
        "loop_lag": tagged("ray_tpu_event_loop_lag_last_seconds",
                           "component"),
        "slo_burn": slo,
    }


def evaluate_health(worker=None) -> Dict[str, Any]:
    """The ``/api/healthz`` payload: this process's verdict plus — on a
    cluster head — per-node verdicts from shipped snapshots, rolled up
    into one cluster status with reasons naming each overloaded
    signal. Always answers; a broken sub-signal degrades to absent
    rather than failing the endpoint."""
    from ray_tpu._private.worker import global_worker

    w = worker or global_worker()
    local = evaluate_signals(_local_signals(w))
    # Component-health providers (serve replica/proxy supervision):
    # dead components degrade this process's verdict with reasons
    # naming them, and recover the moment the provider's list drains.
    extra = provider_reasons()
    if extra:
        local["reasons"] = list(local["reasons"]) + extra
        local["status"] = "degraded"
    nodes: Dict[str, Any] = {}
    head = getattr(w, "cluster_head", None)
    agg = getattr(head, "obs", None) if head is not None else None
    if agg is not None:
        for node_id, snap in sorted(agg.metrics_snapshots().items()):
            try:
                nodes[node_id] = evaluate_signals(
                    _signals_from_snapshot(snap))
            except Exception:
                continue
    reasons = list(local["reasons"])
    for node_id, verdict in nodes.items():
        reasons.extend(f"node {node_id[:8]}: {r}"
                       for r in verdict["reasons"])
    out = {"status": "degraded" if reasons else "ok",
           "reasons": reasons,
           "head": local,
           "nodes": nodes}
    # Structured sections (e.g. "head_shards": per-shard verdicts from
    # the multi-process head's coordinator) ride the payload verbatim.
    out.update(provider_sections())
    # Flight recorder: the ok→degraded edge freezes every live node's
    # rings into one correlated FLIGHT_<ts>.json (no-op unless
    # flight_recorder_dir is configured; debounced inside).
    from ray_tpu._private import flight_recorder

    flight_recorder.observe_verdict(out, worker=w)
    return out
