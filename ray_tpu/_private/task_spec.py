"""Task and actor specifications: the unit handed to the scheduler.

Role-equivalent to the reference's ``TaskSpecification``
(``src/ray/common/task/task_spec.h:182``): everything the execution backend
needs to place and run one invocation — function payload, arguments (inline
values and ObjectRef dependencies), resource request, retry policy, and
scheduling strategy.
"""

from __future__ import annotations

import collections
import enum
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ray_tpu._private import sanitize_hooks
from ray_tpu._private.ids import ActorID, ObjectID, PlacementGroupID, TaskID


class TaskKind(enum.Enum):
    NORMAL_TASK = 0
    ACTOR_CREATION = 1
    ACTOR_TASK = 2


@dataclass
class SchedulingStrategy:
    """Base marker; concrete strategies below.

    Mirrors ``python/ray/util/scheduling_strategies.py``.
    """


@dataclass
class DefaultSchedulingStrategy(SchedulingStrategy):
    pass


@dataclass
class SpreadSchedulingStrategy(SchedulingStrategy):
    pass


@dataclass
class NodeAffinitySchedulingStrategy(SchedulingStrategy):
    node_id: Any = None  # NodeID
    soft: bool = False


@dataclass
class PlacementGroupSchedulingStrategy(SchedulingStrategy):
    placement_group: Any = None
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


def trace_id_of(spec) -> str:
    """A task's trace id: inherited from its submitter, or rooted at
    itself (single source of truth for the derivation)."""
    return spec.trace_parent[0] if spec.trace_parent \
        else spec.task_id.hex()


def trace_parent_from(parent_spec) -> tuple:
    """The submitting task's span becomes the child's parent; the trace
    id is inherited (or rooted at the submitting task)."""
    return (trace_id_of(parent_spec), parent_spec.task_id.hex())


# -- ambient trace context --------------------------------------------------
# Submissions from OUTSIDE any task (e.g. the Serve router dispatching
# an HTTP request to a replica) have no task context to inherit a trace
# from; a thread-local ambient parent bridges the gap, so an ingress
# request's trace id flows proxy → router → replica → any tasks the
# replica submits (reference: tracing_helper.py's context propagation
# through non-task callers).

_AMBIENT_TRACE = threading.local()


def set_ambient_trace_parent(tp: Optional[tuple]) -> Optional[tuple]:
    """Install (trace_id_hex, parent_span_id_hex) as this thread's
    ambient trace parent; returns the previous value for restore."""
    prev = getattr(_AMBIENT_TRACE, "tp", None)
    _AMBIENT_TRACE.tp = tp
    sanitize_hooks.ambient_set("trace_parent", tp)
    return prev


def get_ambient_trace_parent() -> Optional[tuple]:
    return getattr(_AMBIENT_TRACE, "tp", None)


# -- ambient job/tenant context ---------------------------------------------
# Multi-tenant attribution (reference: every TaskSpec carries a JobID
# assigned at driver connect, and the state API slices by it): a spec's
# job tag is inherited from the submitting TASK's spec when the
# submission happens inside a task, so one tag set at the entry point
# flows through arbitrary .remote() chains. Submissions from outside
# any task (a driver, the Serve router dispatching an HTTP request)
# read a thread-local ambient tag — same bridge as the ambient trace
# parent above — with a process-wide default taken from RAY_TPU_JOB_ID,
# the env channel job_submission uses for entrypoint subprocesses.

_AMBIENT_JOB = threading.local()
_default_job_id: "str | None" = None


def default_job_id() -> str:
    """Process-wide fallback job tag: RAY_TPU_JOB_ID when the process
    is a job entrypoint (job_submission sets it), else ""."""
    global _default_job_id
    if _default_job_id is None:
        import os

        _default_job_id = os.environ.get("RAY_TPU_JOB_ID", "")
    return _default_job_id


def set_ambient_job_id(job_id: Optional[str]) -> Optional[str]:
    """Install a job tag for submissions from this thread (None clears
    back to the process default); returns the previous value for
    restore."""
    prev = getattr(_AMBIENT_JOB, "job", None)
    _AMBIENT_JOB.job = job_id
    sanitize_hooks.ambient_set("job_id", job_id)
    return prev


def get_ambient_job_id() -> str:
    job = getattr(_AMBIENT_JOB, "job", None)
    return job if job is not None else default_job_id()


def job_id_for_submit(ctx_spec) -> str:
    """The job tag a new submission carries: the submitting task's own
    tag in-task (executor threads are pooled, so their thread-local
    ambient could belong to an unrelated job), the thread's ambient /
    process default otherwise."""
    if ctx_spec is not None:
        return ctx_spec.job_id or ""
    return get_ambient_job_id()


def check_isolate_process(value):
    """isolate_process accepts False (in-thread), True (forked worker),
    or "spawn" (fresh interpreter); anything else is a typo that would
    otherwise silently fork."""
    if value not in (False, True, "spawn"):
        raise ValueError(
            f"isolate_process must be False, True, or 'spawn', got {value!r}")
    return value


@dataclass
class TaskSpec:
    task_id: TaskID
    kind: TaskKind
    # Callable payload: for normal tasks the function; for actor creation the
    # class; for actor tasks the method name.
    func: Any
    args: tuple
    kwargs: dict
    name: str = ""
    num_returns: "int | str" = 1  # int, or "dynamic" (generator task)
    resources: Dict[str, float] = field(default_factory=dict)
    max_retries: int = 3
    # Which attempt this dispatch is (0 = first). Node-death resubmits
    # and actor-call replays increment it and decrement max_retries —
    # the pair is the per-spec retry ledger, and both ride the wire
    # (TaskCall.attempt / full-spec shipping) so a replayed dispatch is
    # observably a replay on the receiving node too.
    attempt: int = 0
    retry_exceptions: Any = False  # False | True | list of exception types
    scheduling_strategy: SchedulingStrategy = field(
        default_factory=DefaultSchedulingStrategy
    )
    # Actor-related fields
    actor_id: Optional[ActorID] = None
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    actor_name: Optional[str] = None
    namespace: Optional[str] = None
    lifetime: Optional[str] = None  # None | "detached"
    max_pending_calls: int = -1
    # Ordering for actor tasks
    sequence_number: int = 0
    # Runtime env (recorded; applied by the worker pool when it launches
    # dedicated workers for the env)
    runtime_env: Optional[dict] = None
    # Execute in a separate worker process (crash isolation) instead of
    # a thread of the node process: False (in-thread), True (forked), or
    # "spawn" (fresh interpreter — for workloads needing pristine
    # process-global state). Reference: raylet worker_pool.h:156.
    isolate_process: Any = False
    # Return object IDs, precomputed by the submitter (owner)
    return_ids: list = field(default_factory=list)
    # Function-distribution cache key (reference: function_manager
    # export via GCS KV + worker import thread). When set, cluster
    # shipping may strip `func` from the wire copy after the first
    # export — nodes re-resolve it from their cache or the head's KV.
    func_id: Optional[bytes] = None
    # Depth for scheduling fairness / detection of recursive deadlock
    depth: int = 0
    # Distributed tracing: (trace_id_hex, parent_span_id_hex) propagated
    # from the submitting task (reference: tracing_helper.py span
    # context in task metadata).
    trace_parent: Optional[tuple] = None
    # Job/tenant tag: assigned at submission (job_submission entrypoint,
    # Serve ingress, or any ambient scope) and inherited down .remote()
    # chains, so every task/event/metric of one workload is attributable
    # end-to-end. "" = untagged.
    job_id: str = ""
    # Content hash of the interned SpecTemplate this spec was built
    # from, when it was (see intern_template). The cluster wire path
    # ships the template once per node and then references it by this
    # id, so a steady stream of same-shape submissions carries only
    # args + a small header.
    template_id: Optional[bytes] = None

    # Class-level defaults (NOT dataclass fields) for the scheduler's
    # per-spec bookkeeping: quota charge tokens, the sticky admission
    # flag, the submit timestamp, consumed actor restarts, and the
    # milli-demand cache. Hot paths probe these with getattr on every
    # submission/dispatch; an absent instance attribute makes getattr
    # raise-and-catch internally (~µs each), while a class attribute
    # is a plain MRO read. Writes shadow per-instance as before.
    _quota_cpu = None
    _quota_queued = None
    _quota_admitted = False
    _submit_monotonic = None
    _milli_cache = None
    _lease_reroutes = 0
    restarts_used = 0

    def assign_return_ids(self) -> list[ObjectID]:
        """Populate ``return_ids`` from ``num_returns`` and return them.

        Single source of truth for return-id semantics (Worker.submit and
        client-mode ClientWorker.submit both call this — they drifted
        once): num_returns=0 means fire-and-forget (no returns);
        "dynamic" means ONE ref whose value is an ObjectRefGenerator over
        the task's yielded outputs; actor creations always carry at least
        one status object (index 0).
        """
        n = 1 if self.num_returns == "dynamic" else self.num_returns
        if self.kind == TaskKind.ACTOR_CREATION:
            n = max(n, 1)
        self.return_ids = [
            ObjectID.for_task_return(self.task_id, i) for i in range(n)
        ]
        return self.return_ids

    def dependencies(self) -> list[ObjectID]:
        """ObjectIDs appearing at the top level of args/kwargs."""
        return top_level_dependencies(self.args, self.kwargs)

    def nested_dependencies(self, max_depth: int = 4) -> list[ObjectID]:
        """ObjectIDs reachable through standard containers in
        args/kwargs (depth-limited). Used to pin a dispatched task's arg
        objects against a racing driver release; refs buried in custom
        user objects are covered by the executing node's borrower
        registration instead."""
        return nested_dependencies_of(self.args, self.kwargs, max_depth)

    def describe(self) -> str:
        if self.kind == TaskKind.ACTOR_TASK:
            return f"{self.name} (actor={self.actor_id})"
        return f"{self.name} ({self.task_id.hex()[:8]})"


def top_level_dependencies(args, kwargs) -> list[ObjectID]:
    """ObjectIDs at the top level of an args/kwargs pair (shared by
    TaskSpec and QueuedTaskHeader — the dep-gating contract must be
    identical whichever queued form a submission takes)."""
    from ray_tpu.object_ref import ObjectRef

    deps = []
    for a in list(args) + list(kwargs.values()):
        if isinstance(a, ObjectRef):
            deps.append(a.id)
    return deps


def nested_dependencies_of(args, kwargs, max_depth: int = 4) \
        -> list[ObjectID]:
    """Container-walking dependency scan shared by TaskSpec and
    QueuedTaskHeader (see TaskSpec.nested_dependencies)."""
    from ray_tpu.object_ref import ObjectRef

    deps: list[ObjectID] = []
    seen: set = set()

    def walk(v, depth):
        if isinstance(v, ObjectRef):
            if v.binary() not in seen:
                seen.add(v.binary())
                deps.append(v.id)
            return
        if depth <= 0:
            return
        if isinstance(v, (list, tuple, set, frozenset)):
            for item in v:
                walk(item, depth - 1)
        elif isinstance(v, dict):
            for k, item in v.items():
                walk(k, depth - 1)
                walk(item, depth - 1)

    for a in list(args) + list(kwargs.values()):
        walk(a, max_depth)
    return deps


# ---------------------------------------------------------------------------
# Spec-template interning (control-plane fast path)
# ---------------------------------------------------------------------------
#
# Every .remote() call used to rebuild the full invariant slice of its
# TaskSpec — option validation, resource normalization, strategy
# construction — and, in cluster mode, re-serialize all of it per call.
# A SpecTemplate captures that invariant slice ONCE per (callable,
# options) pair, keyed by a content hash, mirroring the reference
# core-worker's serialize-once TaskSpec handling: per-call work shrinks
# to args + a small header referencing the template by id.


@dataclass
class SpecTemplate:
    """The invariant-across-calls slice of a TaskSpec."""

    kind: TaskKind
    func: Any
    name: str
    num_returns: "int | str"
    resources: Dict[str, float]
    milli: Dict[str, int]                 # precomputed to_milli(resources)
    max_retries: int = 3
    retry_exceptions: Any = False
    scheduling_strategy: SchedulingStrategy = None
    runtime_env: Optional[dict] = None
    isolate_process: Any = False
    func_id: Optional[bytes] = None
    # Actor-creation extras (unused for NORMAL_TASK / ACTOR_TASK).
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    actor_name: Optional[str] = None
    namespace: Optional[str] = None
    lifetime: Optional[str] = None
    max_pending_calls: int = -1
    template_id: bytes = b""
    # Lazily-built invariant __dict__ slice for fast materialization
    # (see spec_proto); NOT part of template identity — excluded from
    # dataclass __eq__/__repr__ so a template that has built its proto
    # still compares equal to a content-identical fresh one, and the
    # placeholder-spec dict never rides a wire.TaskTemplate shipment
    # as dead weight.
    _spec_proto: Optional[dict] = field(
        default=None, repr=False, compare=False)

    def make_spec(self, task_id: TaskID, args: tuple, kwargs: dict,
                  depth: int = 0, trace_parent: Optional[tuple] = None,
                  actor_id: Optional[ActorID] = None,
                  sequence_number: int = 0,
                  num_returns: "int | str | None" = None,
                  job_id: str = "") -> TaskSpec:
        """Per-call spec construction: only the varying fields are new."""
        spec = TaskSpec(
            task_id=task_id,
            kind=self.kind,
            func=self.func,
            args=args,
            kwargs=kwargs,
            name=self.name,
            num_returns=self.num_returns if num_returns is None
            else num_returns,
            resources=self.resources,
            max_retries=self.max_retries,
            retry_exceptions=self.retry_exceptions,
            scheduling_strategy=self.scheduling_strategy,
            actor_id=actor_id,
            max_restarts=self.max_restarts,
            max_task_retries=self.max_task_retries,
            max_concurrency=self.max_concurrency,
            actor_name=self.actor_name,
            namespace=self.namespace,
            lifetime=self.lifetime,
            max_pending_calls=self.max_pending_calls,
            sequence_number=sequence_number,
            runtime_env=self.runtime_env,
            isolate_process=self.isolate_process,
            func_id=self.func_id,
            depth=depth,
            trace_parent=trace_parent,
            job_id=job_id,
            template_id=self.template_id,
        )
        # The scheduler's demand conversion, computed once at intern time.
        spec._milli_cache = self.milli
        return spec

    def spec_proto(self) -> dict:
        """The invariant slice of a materialized spec's ``__dict__``,
        built once per template: QueuedTaskHeader.materialize copies it
        with one C-level ``dict.update`` instead of re-running the
        25-kwarg dataclass constructor per dispatch (the constructor
        was ~40% of header+materialize cost; with the proto the compact
        path's TOTAL work is below a single make_spec). Field sharing
        (resources / scheduling_strategy / runtime_env aliased to the
        template's) is exactly make_spec's existing semantics; every
        per-call key is overwritten by the copier. Benign lazy-init
        race: two builders produce equal dicts."""
        proto = self._spec_proto
        if proto is None:
            proto = self.make_spec(TaskID(b"\0" * 16), (), {}).__dict__
            self._spec_proto = proto
        return proto

    def __getstate__(self):
        # The lazily-built proto is derived state: shipping it in a
        # wire.TaskTemplate would carry a placeholder spec __dict__ as
        # dead weight — the receiving side rebuilds on first dispatch.
        state = dict(self.__dict__)
        state["_spec_proto"] = None
        return state


class QueuedTaskHeader:
    """Compact queued form of one submission (the control-plane slice
    of the reference's lease-request header): the interned template
    reference plus only the per-call fields, in a ``__slots__`` object
    a fraction the size of a full ``TaskSpec``. Queued-but-undispatched
    work is held in this form — a million-task backlog costs header
    bytes — and :meth:`materialize` builds the full spec exactly once,
    at dispatch. Only default-strategy NORMAL_TASK submissions take
    this shape (see ``RemoteFunction.remote``); everything else still
    queues full specs, and both forms flow the same scheduler paths
    (quota admission, WFQ classing, dep parking, backlog accounting).

    Retry state (``max_retries``/``attempt``) lives on the header, not
    the template, so node-death resubmits of a leased header keep their
    own ledger; quota charge tokens ride the header and TRANSFER to the
    materialized spec (never both — a charge is released exactly once).
    """

    __slots__ = ("tpl", "task_id", "args", "kwargs", "depth",
                 "trace_parent", "job_id", "attempt", "max_retries",
                 "num_returns", "return_ids", "_milli_cache",
                 "_quota_cpu", "_quota_queued", "_quota_admitted",
                 "_submit_monotonic", "_lease_reroutes")

    def __init__(self, tpl: SpecTemplate, task_id: TaskID, args: tuple,
                 kwargs: dict, depth: int = 0,
                 trace_parent: Optional[tuple] = None,
                 job_id: str = ""):
        self.tpl = tpl
        self.task_id = task_id
        self.args = args
        self.kwargs = kwargs
        self.depth = depth
        self.trace_parent = trace_parent
        self.job_id = job_id
        self.attempt = 0
        self.max_retries = tpl.max_retries
        self.num_returns = tpl.num_returns
        self.return_ids: list = []
        self._milli_cache = tpl.milli
        # Pre-set every optional slot: getattr(h, name, default) on an
        # UNSET slot raises internally (~µs of exception machinery),
        # and the quota/WFQ hot paths probe these on every submission —
        # five stores at mint buy plain reads everywhere after.
        self._quota_cpu = None
        self._quota_queued = None
        self._quota_admitted = False
        self._submit_monotonic = None
        self._lease_reroutes = 0

    # -- template-delegated invariants (read-only views) -----------------

    @property
    def kind(self) -> TaskKind:
        return self.tpl.kind

    @property
    def resources(self) -> Dict[str, float]:
        return self.tpl.resources

    @property
    def scheduling_strategy(self):
        return self.tpl.scheduling_strategy

    @property
    def name(self) -> str:
        return self.tpl.name

    @property
    def func(self):
        return self.tpl.func

    @property
    def func_id(self) -> Optional[bytes]:
        return self.tpl.func_id

    @property
    def template_id(self) -> bytes:
        return self.tpl.template_id

    @property
    def actor_id(self):
        return None  # headers are normal tasks only

    def assign_return_ids(self) -> list[ObjectID]:
        n = 1 if self.num_returns == "dynamic" else self.num_returns
        self.return_ids = [
            ObjectID.for_task_return(self.task_id, i) for i in range(n)
        ]
        return self.return_ids

    def dependencies(self) -> list[ObjectID]:
        return top_level_dependencies(self.args, self.kwargs)

    def nested_dependencies(self, max_depth: int = 4) -> list[ObjectID]:
        return nested_dependencies_of(self.args, self.kwargs, max_depth)

    def describe(self) -> str:
        return f"{self.tpl.name} ({self.task_id.hex()[:8]})"

    def approx_nbytes(self) -> int:
        """Cheap queued-footprint estimate for the
        ``sched_queued_header_bytes`` counter (slots + id + per-arg
        slot; arg VALUES are shared with the caller, not charged)."""
        return 240 + 16 * (len(self.args) + len(self.kwargs))

    def materialize(self, transfer_tokens: bool = True) -> TaskSpec:
        """Build the full TaskSpec. At local dispatch (the default)
        quota charge tokens MOVE to the spec — release/retry paths run
        against the materialized form, exactly once. With
        ``transfer_tokens=False`` (wire copies: the head keeps the
        header in its lineage/in-flight tables) tokens stay put so the
        head-side release still finds the charge."""
        tpl = self.tpl
        proto = tpl._spec_proto
        if proto is None:
            proto = tpl.spec_proto()
        spec = TaskSpec.__new__(TaskSpec)
        d = spec.__dict__
        d.update(proto)
        d["task_id"] = self.task_id
        d["args"] = self.args
        d["kwargs"] = self.kwargs
        d["depth"] = self.depth
        d["trace_parent"] = self.trace_parent
        d["job_id"] = self.job_id
        d["num_returns"] = self.num_returns
        d["return_ids"] = self.return_ids
        d["max_retries"] = self.max_retries
        d["attempt"] = self.attempt
        if transfer_tokens:
            cpu_token = self._quota_cpu
            if cpu_token is not None:
                spec._quota_cpu = cpu_token
                self._quota_cpu = None
            queued_token = self._quota_queued
            if queued_token is not None:
                spec._quota_queued = queued_token
                self._quota_queued = None
        if self._quota_admitted:
            spec._quota_admitted = True
        submitted = self._submit_monotonic
        if submitted is not None:
            spec._submit_monotonic = submitted
        return spec


# Content hash -> template. Interning is by content, so identical
# definitions (same function bytes, same options) share one entry and a
# REdefinition (new body under an old name) can never hit a stale one —
# its func_id, and therefore its template_id, differs. Bounded LRU: a
# driver minting remote functions dynamically (each closure hashes
# differently) must not pin every captured environment forever —
# evicted entries are safe, since live handles hold their template
# strongly and the cluster wire path falls back to full-spec shipping
# on an intern miss.
_TEMPLATES: "collections.OrderedDict[bytes, SpecTemplate]" = \
    collections.OrderedDict()
_TEMPLATES_MAX = 4096
_TEMPLATES_LOCK = threading.Lock()

# Intern hit rate (a low hit rate means per-call template rebuilds are
# back on the hot path — exactly what PR 2 removed).
from ray_tpu._private import perf_stats as _perf_stats  # noqa: E402

_INTERN_HITS = _perf_stats.counter("intern_hits")
_INTERN_MISSES = _perf_stats.counter("intern_misses")


def _strategy_key(strategy) -> str:
    if strategy is None:
        return "None"
    from dataclasses import fields as _fields

    parts = [type(strategy).__name__]
    for f in _fields(strategy):
        parts.append(f"{f.name}={getattr(strategy, f.name)!r}")
    return ":".join(parts)


def intern_template(*, kind: TaskKind, func: Any, name: str,
                    num_returns, resources: Dict[str, float],
                    func_id: Optional[bytes] = None,
                    **invariants) -> SpecTemplate:
    """Build (or reuse) the interned template for one callable + option
    set. The content hash covers the function identity (func_id — the
    sha1 of its cloudpickle — when exportable, else a per-object token)
    and every invariant field, so equal content dedupes and changed
    content gets a fresh id."""
    import hashlib

    from ray_tpu._private.resources import to_milli

    if func_id:
        fn_key = func_id.hex()
    elif isinstance(func, str):
        fn_key = f"method:{func}"   # actor method: the name IS the content
    else:
        fn_key = f"local:{id(func)}"
    h = hashlib.sha1()
    h.update(repr((
        kind.value, fn_key, name, num_returns,
        sorted(resources.items()),
        invariants.get("max_retries", 3),
        repr(invariants.get("retry_exceptions", False)),
        _strategy_key(invariants.get("scheduling_strategy")),
        repr(invariants.get("runtime_env")),
        repr(invariants.get("isolate_process", False)),
        invariants.get("max_restarts", 0),
        invariants.get("max_task_retries", 0),
        invariants.get("max_concurrency", 1),
        invariants.get("actor_name"),
        invariants.get("namespace"),
        invariants.get("lifetime"),
        invariants.get("max_pending_calls", -1),
    )).encode())
    tid = h.digest()
    with _TEMPLATES_LOCK:
        tpl = _TEMPLATES.get(tid)
        if tpl is None:
            _INTERN_MISSES.inc()
        else:
            _INTERN_HITS.inc()
        if tpl is None or tpl.func is not func:
            # Same content but a distinct (equal-bytes) function object:
            # reuse the id, refresh the callable so local execution uses
            # the live object.
            tpl = SpecTemplate(
                kind=kind, func=func, name=name, num_returns=num_returns,
                resources=resources, milli=to_milli(resources),
                func_id=func_id, template_id=tid, **invariants)
        _TEMPLATES[tid] = tpl
        _TEMPLATES.move_to_end(tid)
        while len(_TEMPLATES) > _TEMPLATES_MAX:
            _TEMPLATES.popitem(last=False)
    return tpl


def get_template(template_id: bytes) -> Optional[SpecTemplate]:
    with _TEMPLATES_LOCK:
        tpl = _TEMPLATES.get(template_id)
        if tpl is not None:
            _TEMPLATES.move_to_end(template_id)
        return tpl


def register_template(tpl: SpecTemplate) -> None:
    """Install a template received over the wire (node side)."""
    with _TEMPLATES_LOCK:
        _TEMPLATES[tpl.template_id] = tpl
        _TEMPLATES.move_to_end(tpl.template_id)
        while len(_TEMPLATES) > _TEMPLATES_MAX:
            _TEMPLATES.popitem(last=False)


@dataclass
class Bundle:
    """One placement-group bundle: a resource request reserved on one node."""

    resources: Dict[str, float]
    node_id: Any = None  # filled at reservation time


@dataclass
class PlacementGroupSpec:
    pg_id: PlacementGroupID
    bundles: list
    strategy: str = "PACK"  # PACK | SPREAD | STRICT_PACK | STRICT_SPREAD
    name: str = ""
    lifetime: Optional[str] = None
