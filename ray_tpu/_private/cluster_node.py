"""Worker-node process for multiprocess cluster mode.

Role-equivalent to the reference's raylet + worker pool on one node
(SURVEY.md §1 process topology): registers with the head, executes tasks
submitted over the control plane on a LocalBackend, serves its objects to
peers (owner-based pull — the reference's
`ownership_based_object_directory.h` pattern: the head only stores
*locations*, payloads move node→node directly), and pulls remote
dependencies before dispatch.

Entry: ``python -m ray_tpu._private.cluster_node --head HOST:PORT ...``.
"""

from __future__ import annotations

import argparse
import os
import threading
import time
from typing import Any, Dict, Optional

from ray_tpu._private import sanitize_hooks
from ray_tpu._private import worker as worker_mod
from ray_tpu._private.ids import NodeID, ObjectID
from ray_tpu._private.rpc import RpcClient, RpcServer, routable_host


class NodeRuntime:
    def __init__(self, head_address, resources: Dict[str, float],
                 node_id: Optional[str] = None,
                 shm_name: Optional[str] = None,
                 labels: Optional[Dict[str, str]] = None):
        self.head = RpcClient.to(tuple(head_address))
        self.node_id = node_id or NodeID.from_random().hex()
        # Scheduling labels (e.g. {"ici_slice": "slice-0"} marking which
        # contiguous TPU slice this host belongs to).
        self.labels = dict(labels or {})
        # Objects whose location this node has advertised — replayed to
        # a RESTARTED head (whose location map starts empty).
        self._reported_oids: set = set()

        # Bring up a standard in-process runtime for this node.
        worker_mod.shutdown()
        self.worker = worker_mod.init(**_res_kwargs(resources))
        self.worker.is_cluster_node = True
        # Tenancy quotas are CLUSTER-wide, enforced once at the head's
        # grant/admission path; a node re-enforcing them against its
        # local slice of capacity would double-charge every job.
        self.worker.backend.quota_ledger.disable()
        # Endpoints are advertised at the interface the head routes us
        # on (loopback in single-host simulation, the NIC IP on a real
        # multi-host deployment) — the reference's node manager likewise
        # registers the node's resolved IP, not loopback.
        self._adv_host = routable_host(tuple(head_address))
        self.transfer_addr: Optional[tuple] = None
        self.plane = None
        plane = None
        try:
            from ray_tpu._private.shm_plane import SharedPlane

            if shm_name:
                # Same host as the head: attach its segment — objects
                # move zero-copy between processes with no transfer.
                plane = SharedPlane(shm_name, create=False)
            else:
                # Own segment (remote host, or simulating one): peers
                # reach our objects through the native transfer server.
                # Pulls from this node must take the wire even if the
                # peer's segment happens to be mappable here — that is
                # exactly the remote-host-on-one-machine simulation the
                # same-host fast path would otherwise silently bypass.
                plane = SharedPlane(f"/ray_tpu_node_{os.getpid()}",
                                    create=True)
                plane.allow_local_pull = False
            # Server first, install last: if anything here raises the
            # worker has not been touched yet.
            port = plane.store.start_transfer_server()
            plane.install(self.worker)
            self.transfer_addr = (self._adv_host, port)
            self.plane = plane
        except Exception:
            # Heap/RPC path is still correct — but don't leak a
            # half-installed plane or an orphaned /dev/shm segment.
            if plane is not None:
                if getattr(self.worker, "shm_plane", None) is plane:
                    self.worker.shm_plane = None
                try:
                    if shm_name:
                        plane.close()      # attached: owner cleans up
                    else:
                        plane.destroy()    # ours: unlink the segment
                except Exception:
                    pass
            self.transfer_addr = None
        self._fn_cache: Dict[bytes, Any] = {}  # function-import cache
        # Interned spec templates received over the wire (the
        # serialize-once TaskSpec cache): template_id -> SpecTemplate.
        # LRU at 2x the head's per-node claim bound: every template
        # carries a pickled user callable + captured environment, so an
        # unbounded cache would grow node RSS forever under dynamic
        # function minting; the capacity margin keeps every id the head
        # still claims resident (both sides touch in the same order).
        from ray_tpu._private.rpc import LruTable

        self._spec_templates = LruTable(8192)
        self._shutdown_event = threading.Event()
        self._install_report_hook()
        self._install_spill_report()
        self._install_borrow_hooks()
        self._install_cluster_actor_routing()
        self._install_cluster_kv()
        self._install_fetch_on_get()
        self._install_cluster_named_actors()

        self.server = RpcServer({
            "submit_task": self._submit_task,
            "submit_batch": self._submit_batch,
            "get_object": self._get_object,
            "get_objects_batch": self._get_objects_batch,
            "contains_object": self._contains_object,
            "free_objects": self._free_objects,
            "kill_actor": self._kill_actor,
            "prepare_bundle": self._prepare_bundle,
            "commit_bundle": self._commit_bundle,
            "return_bundle": self._return_bundle,
            "ping": self._ping,
            "flight_snapshot": self._flight_snapshot,
            "shutdown": self._shutdown,
        }, host="0.0.0.0",
           dedupe_methods=frozenset({"submit_task", "submit_batch",
                                     "kill_actor"}))
        # 2PC bundle reservation state: (pg_id, idx) -> milli request held
        # in "prepared" until commit or return (reference:
        # `raylet/placement_group_resource_manager.h`).
        self._prepared_bundles: Dict[tuple, Dict[str, int]] = {}
        # Advertised control address (bind is all-interfaces).
        self.address = (self._adv_host, self.server.address[1])
        # Registration is idempotent; retry through transient head
        # unavailability during cluster bring-up.
        from ray_tpu._private.config import ray_config

        last_err: Optional[BaseException] = None
        plane = getattr(self.worker, "shm_plane", None)
        for _ in range(ray_config.rpc_connect_retries):
            try:
                self.head.call("register_node", node_id=self.node_id,
                               address=self.address,
                               resources=resources,
                               transfer=self.transfer_addr,
                               shm_name=plane.name if plane else None,
                               labels=self.labels)
                # Events recorded in THIS process (e.g. a serve
                # controller actor placed here) must reach the head's
                # observable buffer, not die in a local deque.
                from ray_tpu._private import events as _events

                head = self.head
                _events.set_forwarder(
                    lambda **kw: head.call("gcs_record_event", **kw))
                # Observability shipping: task-event deltas + metric
                # snapshots flow to the head's aggregator so timeline/
                # tracing/state/dashboard views are cluster-wide. Shares
                # the node's shutdown event — the loop's exit path ships
                # the final terminal states.
                from ray_tpu._private.obs_plane import NodeObsShipper

                self.obs_shipper = NodeObsShipper(
                    self.worker, tuple(head_address), self.node_id,
                    stop_event=self._shutdown_event).start()
                break
            except Exception as e:
                last_err = e
                time.sleep(ray_config.rpc_retry_backoff_s)
        else:
            raise RuntimeError(
                f"node {self.node_id} could not register with head at "
                f"{head_address}: {last_err}")

    # -- object plane ----------------------------------------------------

    def _install_report_hook(self):
        """Report object locations to the head as task outputs land."""
        worker = self.worker
        orig = worker.store_task_outputs
        node = self
        # Output reports BATCH across tasks (reference: raylet object
        # report batching): at fan-out rates a synchronous head RPC per
        # task serializes every executor thread behind the report
        # connection. A dedicated reporter flushes accumulated oids
        # every couple of ms — results become cluster-visible one batch
        # later, execution never blocks on the head.
        import queue as _q

        report_q: "_q.SimpleQueue" = _q.SimpleQueue()

        def report_loop():
            while True:
                items = [report_q.get()]
                t0 = time.monotonic()
                while time.monotonic() - t0 < 0.002:
                    try:
                        items.append(report_q.get_nowait())
                    except _q.Empty:
                        time.sleep(0.0005)
                # Borrow registrations first: the output report unpins
                # these tasks' args at the head, so any borrow they
                # created must be on record before that (same head
                # connection → ordered).
                getattr(node, "_flush_borrows", lambda: None)()
                try:
                    # Sizes ride the report: the head's directory feeds
                    # locality-aware placement (bytes, not just where).
                    node.head.call("report_objects",
                                   oids=[ob for ob, _ in items],
                                   address=node.address,
                                   sizes=[sz for _, sz in items])
                except Exception:
                    pass

        threading.Thread(target=report_loop, daemon=True,
                         name="output-reporter").start()

        def store_and_report(spec, values, error=None):
            orig(spec, values, error=error)
            # Primary-copy pin (reference: plasma primary copies stay
            # pinned until the owner frees them): local handle churn (an
            # actor holding then releasing a ref to an object that lives
            # here) must never evict the only copy; the head's
            # free_objects is what drops it.
            dynamic = list(getattr(spec, "dynamic_return_ids", ()))
            for roid in list(spec.return_ids) + dynamic:
                worker.memory_store.pin_object(roid)
            returns = list(spec.return_ids) + dynamic
            if returns:
                node._reported_oids.update(r.binary() for r in returns)
                for roid in returns:
                    report_q.put((roid.binary(),
                                  worker.memory_store.entry_size(roid)))

        worker.store_task_outputs = store_and_report

    def _install_spill_report(self):
        """Spilled objects report their durable URL to the head: if
        this node later dies, the head restores the lost object from
        the surviving disk copy instead of re-executing its creating
        task (reconstruction-composes-with-spill). Reports COALESCE on
        a drainer thread (same shape as the output reporter): one
        pressure sweep spilling dozens of objects makes one RPC, not
        one per object, and the spill path never blocks on the head."""
        import queue as _q

        node = self
        report_q: "_q.SimpleQueue" = _q.SimpleQueue()

        def report_loop():
            while True:
                items = [report_q.get()]
                t0 = time.monotonic()
                while time.monotonic() - t0 < 0.05:
                    try:
                        items.append(report_q.get_nowait())
                    except _q.Empty:
                        time.sleep(0.005)
                try:
                    node.head.call("report_spilled",
                                   oids=[ob for ob, _ in items],
                                   urls=[u for _, u in items],
                                   node_id=node.node_id)
                except Exception:
                    pass  # best effort: re-execution remains the net

        threading.Thread(target=report_loop, daemon=True,
                         name="spill-reporter").start()
        self.worker.memory_store.on_spilled = \
            lambda object_id, url: report_q.put((object_id.binary(),
                                                 url))

    def _install_borrow_hooks(self):
        """Register this node as a borrower of every object it holds a
        handle to (reference: ReferenceCounter borrower protocol). A ref
        deserialized here (task arg, value inside actor state) adds this
        node to the head's borrower set for its object; the last local
        handle dropping removes it.

        Reporting is LEVEL-based, not edge-based: hooks only mark an oid
        "touched"; the flush consults the store's current handle count
        and diffs against what the head was last told. This is immune to
        drop-then-reacquire races inside one flush window (an edge queue
        could deliver add+remove in the wrong order), and a failed flush
        simply re-touches the batch for the next round. Adds are flushed
        BEFORE task-output reports on the same head connection, so the
        head never unpins a task's args before learning about a borrow
        the task created."""
        worker = self.worker
        node = self
        orig_register = worker.register_object_ref
        orig_unregister = worker.unregister_object_ref
        touched: set = set()
        reported: set = set()  # oids the head believes we borrow
        lock = threading.Lock()
        flush_lock = threading.Lock()  # one flush at a time (loop +
        #                                pre-report flushes can race)
        from ray_tpu._private.ids import ObjectID as _OID

        def flush():
            with flush_lock:
                _flush_inner()  # raylint: disable=R2 -- flush_lock exists ONLY to serialize this flush RPC (loop + pre-report flushes race); nothing else ever contends on it, so holding it across the head call is its entire job

        def _flush_inner():
            with lock:
                batch = list(touched)
                touched.clear()
            if not batch:
                return
            adds, removes = [], []
            for ob in batch:
                holding = worker.memory_store.local_ref_count(
                    _OID(ob)) > 0
                if holding and ob not in reported:
                    adds.append(ob)
                elif not holding and ob in reported:
                    removes.append(ob)
            try:
                if adds:
                    node.head.call("add_borrowers", oids=adds,
                                   node_id=node.node_id)
                    reported.update(adds)
                if removes:
                    node.head.call("remove_borrowers", oids=removes,
                                   node_id=node.node_id)
                    reported.difference_update(removes)
            except Exception:
                # Head unreachable: nothing was dropped — re-touch so the
                # next flush retries (a lost add would let the head free
                # a borrowed object; a lost remove would leak it).
                with lock:
                    touched.update(batch)

        def register(ref):
            count = orig_register(ref)
            if count == 1:
                with lock:
                    touched.add(ref.id.binary())
            return count

        def unregister(oid):
            zero = orig_unregister(oid)
            if zero:
                with lock:
                    touched.add(oid.binary())
            return zero

        worker.register_object_ref = register
        worker.unregister_object_ref = unregister
        self._flush_borrows = flush

        def flush_loop():
            while not self._shutdown_event.wait(0.2):
                flush()

        threading.Thread(target=flush_loop, daemon=True,
                         name="borrow-flush").start()

    def _fetch_dependency(self, oid: ObjectID,
                          timeout: Optional[float] = None):
        from ray_tpu._private.config import ray_config

        if self.worker.memory_store.contains(oid):
            return
        if timeout is None:
            timeout = ray_config.fetch_deadline_s
        deadline = time.monotonic() + timeout
        attempt = 0
        while time.monotonic() < deadline:
            if self.worker.memory_store.contains(oid):
                return  # produced locally while we were polling
            from ray_tpu.cluster_utils import (fetch_backoff,
                                               try_shm_fetch,
                                               try_transfer_fetch)

            if try_shm_fetch(self.worker, oid):
                return
            # Local probes (memory store, shm) are cheap and run every
            # attempt; the head locate RPC is rate-limited to every 4th
            # fine-grained probe so sub-ms polling doesn't turn into an
            # RPC storm.
            if attempt % 4 == 0:
                info = self.head.call("locate2", oid=oid.binary())
                if info is not None and \
                        tuple(info["address"]) != self.address:
                    if try_transfer_fetch(self.worker, oid, info):
                        return
                    ok, value, err = RpcClient.to(
                        tuple(info["address"])).call(
                        "get_object", oid=oid.binary())
                    if ok:
                        self.worker.memory_store.put(oid, value,
                                                     error=err)
                        return
            fetch_backoff(attempt)
            attempt += 1
        raise TimeoutError(f"could not fetch {oid.hex()} from cluster")

    # -- RPC handlers ----------------------------------------------------

    def _submit_task(self, spec):
        from ray_tpu.object_ref import ObjectRef

        if spec.func is None and getattr(spec, "func_id", None):
            spec.func = self._resolve_function(spec.func_id)
        elif spec.func is not None and getattr(spec, "func_id", None):
            # Prime the cache from the full-body first shipment so the
            # first STRIPPED spec doesn't pay a head-KV round trip on
            # the dispatch hot path.
            self._fn_cache[spec.func_id] = spec.func
        deps = [arg.id for arg in
                list(spec.args) + list(spec.kwargs.values())
                if isinstance(arg, ObjectRef)]
        missing = [d for d in deps
                   if not self.worker.memory_store.contains(d)]
        submit = getattr(self, "_orig_backend_submit",
                         self.worker.backend.submit)
        if not missing:
            submit(spec)
            return True

        # Pull remote deps off the RPC thread: ack immediately so the
        # driver isn't blocked on our fetches (the reference's
        # DependencyManager is likewise async). The batched fetch
        # resolves ALL missing args with one locate RPC + one pull per
        # owner, not one round trip per argument.
        def fetch_then_submit():
            try:
                self._fetch_dependencies(missing)
                submit(spec)
            except BaseException as e:  # noqa: BLE001
                from ray_tpu import exceptions as exc

                self.worker.store_task_outputs(
                    spec, None,
                    error=exc.TaskError(e, spec.describe()))

        threading.Thread(target=fetch_then_submit, daemon=True).start()
        return True

    # -- batched submission (interned templates + coalesced frames) ------

    def _submit_batch(self, templates=None, calls=None):
        """One coalesced frame of task submissions. Templates register
        first (a frame always carries a template before the first call
        referencing it); calls then dispatch in order. Per-call failures
        land in that call's return objects — the frame itself only fails
        on transport/decode problems, where nothing was dispatched."""
        # Yield point at the frame boundary: everything before this
        # crossing is "the frame arrived but nothing dispatched" —
        # where a node death leaves the driver's exactly-once resubmit
        # (same frame rid, server-side dedupe) to do the recovery.
        sanitize_hooks.sched_point("cluster.submit_batch")
        for t in templates or []:
            payload = t.payload
            if payload is not None:
                self._spec_templates.add(t.template_id, payload)
        for c in calls or []:
            try:
                from ray_tpu._private import wire

                spec = self._spec_from_call(c) \
                    if isinstance(c, wire.TaskCall) else c
                self._submit_task(spec)
            except BaseException as e:  # noqa: BLE001 — isolate per call
                self._fail_call(c, e)
        return True

    def _spec_from_call(self, call):
        tpl = self._spec_templates.get(call.template_id)
        if tpl is None:
            raise RuntimeError(
                f"UnknownTemplateError: {call.template_id.hex()[:12]} "
                "not registered on this node")
        from ray_tpu._private.config import ray_config
        from ray_tpu._private.ids import TaskID

        if ray_config.sched_compact_queue:
            # Node-side compact queueing: the wire call stays a header
            # until this node's scheduler dispatches it, so a deep
            # remote backlog is header-sized here too.
            from ray_tpu._private.task_spec import QueuedTaskHeader

            spec = QueuedTaskHeader(
                tpl, TaskID(call.task_id),
                tuple(call.args or ()),
                dict(call.kwargs or {}),
                depth=call.depth,
                trace_parent=tuple(call.trace_parent)
                if call.trace_parent else None,
                job_id=getattr(call, "job_id", "") or "",
            )
            if call.num_returns is not None:
                spec.num_returns = call.num_returns
        else:
            spec = tpl.make_spec(
                TaskID(call.task_id),
                tuple(call.args or ()),
                dict(call.kwargs or {}),
                depth=call.depth,
                trace_parent=tuple(call.trace_parent)
                if call.trace_parent else None,
                num_returns=call.num_returns,
                job_id=getattr(call, "job_id", "") or "",
            )
        spec.max_retries = call.max_retries
        spec.attempt = getattr(call, "attempt", 0) or 0
        spec.assign_return_ids()
        return spec

    def _fail_call(self, c, e: BaseException):
        """Fail one batch item into its return objects (num_returns
        rides on the call precisely so this works without the
        template)."""
        from types import SimpleNamespace

        from ray_tpu import exceptions as exc
        from ray_tpu._private import wire
        from ray_tpu._private.ids import TaskID

        try:
            if isinstance(c, wire.TaskCall):
                n = 1 if c.num_returns == "dynamic" else int(c.num_returns)
                n = max(n, 1)
                tid = TaskID(c.task_id)
                return_ids = [ObjectID.for_task_return(tid, i)
                              for i in range(n)]
                desc = f"task {tid.hex()[:8]} (batched)"
            else:
                return_ids = list(c.return_ids) or c.assign_return_ids()
                desc = c.describe()
            self.worker.store_task_outputs(
                SimpleNamespace(return_ids=return_ids,
                                dynamic_return_ids=()),
                None, error=exc.TaskError(e, desc))
        except Exception:
            pass  # best effort: the head's fetch deadline is the backstop

    def _fetch_dependencies(self, oids, timeout=None):
        """Batched arg-fetch: resolve every missing dependency with ONE
        head locate RPC for the whole set, then one batched pull per
        owner node (reference: PullManager batches object requests) —
        the shared core in cluster_utils. Anything still unresolved (or
        whose owner errored) falls back to the per-object polling fetch
        (slow producers, racing relocation)."""
        from ray_tpu.cluster_utils import batch_fetch_objects

        def locate(need):
            try:
                return self.head.call(
                    "locate_batch", oids=[o.binary() for o in need])
            except Exception:
                return [None] * len(need)

        _resolved, failed, unresolved = batch_fetch_objects(
            self.worker, oids, locate, self.address)
        for oid in list(failed) + unresolved:
            self._fetch_dependency(oid, timeout)

    def _install_cluster_actor_routing(self):
        """Actor handles work from ANY process (reference: the direct
        actor transport reaches actors wherever they live). A task here
        holding a handle to an actor that does NOT live in this node
        routes the call through the head, whose cluster backend knows
        every actor's home; results come back over the object plane."""
        backend = self.worker.backend
        node = self
        orig_submit = backend.submit
        # Submissions ARRIVING over RPC (the head directed them here)
        # must bypass the wrapper: routing them back to the head when a
        # creation's mailbox isn't registered yet would ping-pong
        # head<->node in nested blocking RPCs.
        self._orig_backend_submit = orig_submit

        def submit(spec):
            from ray_tpu._private.task_spec import TaskKind

            if spec.kind == TaskKind.ACTOR_TASK and \
                    spec.actor_id not in backend._actors:
                node.head.call("route_task", spec=spec)
                return
            if spec.kind == TaskKind.ACTOR_CREATION:
                # A locally-created actor must exist in the head's
                # directory or handles to it can't route from other
                # processes.
                orig_submit(spec)
                for attempt in range(3):
                    try:
                        node.head.call("report_actor", spec=spec,
                                       node_id=node.node_id)
                        break
                    except Exception:
                        # Unregistered = unroutable from every other
                        # process; worth a few retries and a loud log.
                        if attempt == 2:
                            import logging

                            logging.getLogger(__name__).warning(
                                "could not register actor %s with the "
                                "head; remote handles to it will fail",
                                spec.actor_id.hex()[:8])
                        time.sleep(0.2)
                return
            orig_submit(spec)

        backend.submit = submit

    def _install_fetch_on_get(self):
        """On-demand remote-object fetch for get()/wait() issued INSIDE
        node code (e.g. a routed actor call's result): the dep-fetch
        machinery covers task ARGUMENTS; this covers refs acquired
        mid-task. Mirrors the driver's ClusterDriverMixin."""
        worker = self.worker
        node = self
        fetching: set = set()
        lock = threading.Lock()

        def ensure_fetch(ref):
            if worker.memory_store.contains(ref.id):
                return
            key = ref.id.binary()
            with lock:
                if key in fetching:
                    return
                fetching.add(key)

            def fetch(oid=ref.id):
                from ray_tpu import exceptions as exc

                try:
                    node._fetch_dependency(oid)
                except TimeoutError:
                    # Deadline expiry is NOT evidence of a dead owner —
                    # the producer may simply still be running. Give up
                    # quietly (the caller's own get timeout governs);
                    # dropping the fetching entry lets a later get
                    # retry. Poisoning here would fail healthy slow
                    # calls AND stick for every later reader.
                    pass
                except BaseException as e:  # noqa: BLE001
                    if not worker.memory_store.contains(oid):
                        worker.memory_store.put(
                            oid, None, error=exc.OwnerDiedError(
                                oid.hex()[:12],
                                f"fetch failed on node "
                                f"{node.node_id}: {e}"))
                finally:
                    with lock:
                        fetching.discard(key)

            threading.Thread(target=fetch, daemon=True).start()

        original_get = worker.get_objects
        original_wait = worker.wait

        def get_objects(refs, timeout=None):
            for ref in refs:
                ensure_fetch(ref)
            return original_get(refs, timeout)

        def wait(refs, num_returns, timeout, *args, **kw):
            for ref in refs:
                ensure_fetch(ref)
            return original_wait(refs, num_returns, timeout, *args,
                                 **kw)

        worker.get_objects = get_objects
        worker.wait = wait

    def _install_cluster_kv(self):
        """Internal KV is a CLUSTER-wide table living on the head
        (reference: gcs_kv_manager.h behind the GCS client); node-local
        kv_put/get/del/keys delegate there so components running on any
        node (e.g. the serve controller's checkpoints) read and write
        the same — durable, when configured — store."""
        gcs = self.worker.gcs
        head = self.head
        gcs.kv_put = lambda key, value, overwrite=True, namespace=None: \
            head.call("gcs_kv_put", key=key, value=value,
                      overwrite=overwrite, namespace=namespace)
        gcs.kv_get = lambda key, namespace=None: \
            head.call("gcs_kv_get", key=key, namespace=namespace)
        gcs.kv_del = lambda key, namespace=None: \
            head.call("gcs_kv_del", key=key, namespace=namespace)
        gcs.kv_keys = lambda prefix, namespace=None: \
            head.call("gcs_kv_keys", prefix=prefix, namespace=namespace)

    def _install_cluster_named_actors(self):
        """Named actors are a CLUSTER-wide registry (reference:
        GcsActorManager named actors); node-local registrations/lookups
        delegate to the head."""
        gcs = self.worker.gcs
        head = self.head

        def register(name, namespace, handle):
            head.call("gcs_named_actor_register", name=name,
                      namespace=namespace, handle=handle)

        def get(name, namespace):
            try:
                return head.call("gcs_named_actor_get", name=name,
                                 namespace=namespace)
            except Exception as e:
                raise ValueError(
                    f"Failed to look up actor {name!r}") from e

        def list_named(all_namespaces=False):
            return head.call("gcs_named_actors",
                             all_namespaces=all_namespaces)

        def remove_by_id(actor_id):
            head.call("gcs_named_actor_remove",
                      actor_id=actor_id.binary())

        gcs.register_named_actor = register
        gcs.get_named_actor = get
        gcs.list_named_actors = list_named
        gcs.remove_named_actor_by_id = remove_by_id

    def _resolve_function(self, fid: bytes):
        """Function-distribution import side (reference: the worker
        import thread pulling exported definitions from GCS KV). Specs
        shipped without a body resolve here: process cache first, head
        KV on miss."""
        fn = self._fn_cache.get(fid)
        if fn is None:
            import cloudpickle

            blob = self.head.call("gcs_kv_get", key=fid,
                                  namespace=b"__fn__")
            if blob is None:
                raise RuntimeError(
                    f"function {fid.hex()[:12]} not found in the "
                    "cluster function store")
            fn = cloudpickle.loads(blob)
            self._fn_cache[fid] = fn
        return fn

    def _get_object(self, oid: bytes, timeout: float = 30.0):
        object_id = ObjectID(oid)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            ready, value, error = self.worker.memory_store.peek(object_id)
            if ready:
                return True, value, error
            time.sleep(0.005)
        return False, None, None

    def _get_objects_batch(self, oids, timeout: float = 30.0,
                           shm=None, can_pull: bool = False):
        """Batched peer read: one RPC returns, per object, either an
        ObjectDescriptor (requester can reach the sealed bytes — same
        segment or our transfer server) or (ok, value, error) with the
        framed-pickle value for small/plane-less objects."""
        from ray_tpu.cluster_utils import descriptor_object_read

        return descriptor_object_read(
            self.worker, self.transfer_addr,
            lambda oid, t: self._get_object(oid, timeout=t), oids,
            timeout, shm=shm, can_pull=can_pull)

    def _contains_object(self, oid: bytes):
        return self.worker.memory_store.contains(ObjectID(oid))

    def _free_objects(self, oids):
        """Drop objects whose driver-side refcount hit zero (the head
        fans the release out to owners — reference: FreeObjects RPC,
        `object_manager.proto:61`)."""
        object_ids = [ObjectID(o) for o in oids]
        self._reported_oids.difference_update(oids)
        self.worker.memory_store.evict(object_ids)
        plane = getattr(self.worker, "shm_plane", None)
        if plane is not None:
            for object_id in object_ids:
                try:
                    # Owner-side free: drop the pin AND reclaim the
                    # arena block (a released-but-undeleted object only
                    # leaves under later LRU pressure).
                    plane.evict_object(object_id)
                except Exception:
                    pass
        return True

    def _kill_actor(self, actor_id, no_restart: bool = True):
        self.worker.backend.kill_actor(actor_id, no_restart)
        return True

    # -- placement-group 2PC (prepare / commit / return) -----------------

    def _prepare_bundle(self, pg_id: bytes, index: int, request):
        """Phase 1: tentatively acquire the bundle's resources."""
        key = (pg_id, index)
        if key in self._prepared_bundles:
            return True  # idempotent retry
        milli = {k: int(v) for k, v in request.items()}
        if self.worker.backend.resources.try_acquire(milli):
            self._prepared_bundles[key] = milli
            return True
        return False

    def _commit_bundle(self, pg_id: bytes, index: int, bundle):
        """Phase 2: convert the held resources into a bundle pool tasks
        can target via PlacementGroupSchedulingStrategy."""
        from ray_tpu._private.ids import PlacementGroupID
        from ray_tpu._private.resources import ResourceSet

        key = (pg_id, index)
        if key not in self._prepared_bundles:
            return False
        self._prepared_bundles.pop(key)
        self.worker.backend.bundle_resources[
            (PlacementGroupID(pg_id), index)] = ResourceSet(bundle)
        return True

    def _return_bundle(self, pg_id: bytes, index: int):
        """Abort a prepared bundle, or release a committed one."""
        from ray_tpu._private.ids import PlacementGroupID
        from ray_tpu._private.resources import to_milli

        key = (pg_id, index)
        held = self._prepared_bundles.pop(key, None)
        if held is not None:
            self.worker.backend.resources.release(held)
            return True
        pool = self.worker.backend.bundle_resources.pop(
            (PlacementGroupID(pg_id), index), None)
        if pool is not None:
            self.worker.backend.resources.release(to_milli(pool.total))
            return True
        return False

    def _ping(self):
        return {
            "node_id": self.node_id,
            "available": self.worker.backend.resources.available,
            "total": self.worker.backend.resources.total,
            "labels": self.labels,
        }

    def _flight_snapshot(self):
        """Freeze this node's flight-recorder rings (recent stage
        spans + health samples + slow in-flight waterfalls) for the
        head's correlated FLIGHT_<ts>.json post-mortem dump."""
        from ray_tpu._private import flight_recorder

        return flight_recorder.local_snapshot()

    def _shutdown(self):
        self._shutdown_event.set()
        return True

    # -- lifecycle -------------------------------------------------------

    def _resource_report_loop(self):
        """Push the availability view to the head (reference:
        ray_syncer.h RESOURCE_VIEW deltas). Doubles as a heartbeat; only
        deltas are sent (an unchanged view is skipped, with a periodic
        keepalive so the head's freshness window stays warm)."""
        from ray_tpu._private.config import ray_config

        last_sent = None
        last_time = 0.0
        while not self._shutdown_event.wait(
                max(ray_config.resource_report_period_s, 0.01)):
            view = dict(self.worker.backend.resources.available)
            keepalive = time.monotonic() - last_time > \
                ray_config.resource_report_period_s * \
                (ray_config.resource_report_fresh_periods / 2)
            if view == last_sent and not keepalive:
                continue
            try:
                from ray_tpu._private.node_stats import sample_node_stats

                # Backlog rides the report (reference: raylet backlog
                # reports in lease requests): queued-not-running task
                # count, so lease grants see queue depth, not just the
                # resource view.
                backlog = self.worker.backend.backlog_count()
                ok = self.head.call("report_resources",
                                    node_id=self.node_id,
                                    available=view, labels=self.labels,
                                    stats=sample_node_stats(),
                                    backlog=backlog)
                last_sent = view
                last_time = time.monotonic()
                if ok is False:
                    # Head lost us (restart?): re-register and
                    # re-publish our state.
                    self._reregister()
            except Exception:
                pass

    def _reregister(self):
        """Re-join a restarted head (reference:
        `node_manager.proto:356` RayletNotifyGCSRestart → raylets
        re-publish). Registration alone rebuilds only the node table;
        the head's actor directory and object-location map started
        empty, so re-report every hosted actor (restoring routing AND
        restart bookkeeping via record_lineage) and every object this
        node still owns."""
        plane = getattr(self.worker, "shm_plane", None)
        self.head.call(
            "register_node", node_id=self.node_id,
            address=self.address,
            resources=dict(self.worker.backend.resources.total),
            transfer=self.transfer_addr,
            shm_name=plane.name if plane else None,
            labels=self.labels)
        # Consumed-restart count = head-driven restarts recorded on
        # the spec + this node's own in-place worker restarts: the
        # fresh head's gate seeds the REMAINING budget, not a reset
        # one. Re-reports BATCH into one report_actors RPC (group-
        # committed registration: a node hosting 10k actors reconverges
        # in O(1) round trips, not O(actors)); old heads without the
        # batch handler get the per-actor fallback.
        live = [(actor.spec,
                 getattr(actor.spec, "restarts_used", 0)
                 + actor.num_restarts)
                for actor in list(getattr(self.worker.backend,
                                          "_actors", {}).values())
                if actor.state != "DEAD"]
        try:
            if live:
                self.head.call(
                    "report_actors",
                    specs=[spec for spec, _ in live],
                    node_id=self.node_id,
                    restarts_used=[used for _, used in live])
        except Exception:
            for spec, used in live:
                try:
                    self.head.call("report_actor", spec=spec,
                                   node_id=self.node_id,
                                   restarts_used=used)
                except Exception:
                    pass
        oids = [oid for oid in self._reported_oids
                if self.worker.memory_store.contains(ObjectID(oid))]
        if oids:
            try:
                # Sizes ride the re-report too: a head (or head SHARD)
                # that lost its directory needs bytes back, not just
                # locations — locality-aware placement and the sharded
                # head's re-registration repair path both read them.
                sizes = [self.worker.memory_store.entry_size(
                    ObjectID(oid)) for oid in oids]
                self.head.call("report_objects", oids=oids,
                               address=self.address, sizes=sizes)
            except Exception:
                pass

    def serve_forever(self):
        """Serve until shutdown — or until the head stays unreachable
        past the health window (a dead head orphans the node; exiting
        mirrors the reference raylet's GCS-disconnect suicide)."""
        from ray_tpu._private.config import ray_config

        reporter = threading.Thread(target=self._resource_report_loop,
                                    daemon=True, name="resource-report")
        reporter.start()
        misses = 0
        try:
            while not self._shutdown_event.wait(
                    max(ray_config.health_check_period_s, 0.1)):
                try:
                    self.head.call("get_nodes")
                    misses = 0
                except Exception:
                    misses += 1
                    if misses >= 4 * \
                            ray_config.health_check_failure_threshold:
                        break
        finally:
            self.server.shutdown()
            plane = getattr(self, "plane", None)
            if plane is not None:
                if plane._owner:
                    plane.destroy()
                else:
                    plane.close()
            worker_mod.shutdown()


def _res_kwargs(resources: Dict[str, float]) -> dict:
    kw: Dict[str, Any] = {}
    res = dict(resources)
    if "CPU" in res:
        kw["num_cpus"] = res.pop("CPU")
    if "TPU" in res:
        kw["num_tpus"] = res.pop("TPU")
    if res:
        kw["resources"] = res
    return kw


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--head", required=True)
    parser.add_argument("--num-cpus", type=float, default=1)
    parser.add_argument("--num-tpus", type=float, default=0)
    parser.add_argument("--node-id", default=None)
    parser.add_argument("--shm-name", default=None)
    parser.add_argument("--label", action="append", default=[],
                        help="node label key=value (repeatable)")
    args = parser.parse_args()
    host, port = args.head.rsplit(":", 1)
    resources = {"CPU": args.num_cpus}
    if args.num_tpus:
        resources["TPU"] = args.num_tpus
    labels = dict(kv.split("=", 1) for kv in args.label)
    runtime = NodeRuntime((host, int(port)), resources,
                          node_id=args.node_id, shm_name=args.shm_name,
                          labels=labels)
    runtime.serve_forever()


if __name__ == "__main__":
    main()
