"""Driver-side log mirroring.

Role-equivalent to the reference's log monitor
(`python/ray/_private/log_monitor.py:1`): worker/node output is written
to per-node files; the driver tails them and re-prints each line with a
node prefix, so `print()` inside a task on any node shows up in the
driver's terminal — the reference's day-one usability contract.

The monitor polls registered files (cheap: one stat per file per tick)
and survives rotation/truncation by re-seeking when the file shrinks.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, Optional


class LogMonitor:
    def __init__(self, *, poll_interval_s: float = 0.25,
                 sink: Optional[Callable[[str], None]] = None):
        self._files: Dict[str, str] = {}  # prefix -> path
        self._offsets: Dict[str, int] = {}
        self._interval = poll_interval_s
        self._sink = sink or (lambda line: print(line, flush=True))
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._partial: Dict[str, str] = {}
        self._thread: Optional[threading.Thread] = None

    def add_file(self, prefix: str, path: str) -> None:
        with self._lock:
            self._files[prefix] = path
            self._offsets.setdefault(prefix, 0)

    def start(self) -> "LogMonitor":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="log-monitor")
            self._thread.start()
        return self

    def stop(self, drain: bool = True) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout=5)
        if drain:
            # Final sweep: exit output must not vanish. Each pass reads
            # at most 1 MB per file, so loop until nothing advances.
            for _ in range(64):
                before = dict(self._offsets)
                self._poll_once()
                if self._offsets == before:
                    break

    # -- internals -------------------------------------------------------

    def _loop(self):
        while not self._stop.is_set():
            self._poll_once()
            self._stop.wait(self._interval)

    def _poll_once(self):
        with self._lock:
            files = dict(self._files)
        for prefix, path in files.items():
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            offset = self._offsets.get(prefix, 0)
            if size < offset:
                offset = 0  # truncated/rotated: start over
            if size == offset:
                continue
            try:
                with open(path, "rb") as f:
                    f.seek(offset)
                    chunk = f.read(min(size - offset, 1 << 20))
            except OSError:
                continue
            self._offsets[prefix] = offset + len(chunk)
            text = self._partial.pop(prefix, "") + \
                chunk.decode("utf-8", "replace")
            lines = text.split("\n")
            # Hold back a trailing partial line until its newline lands.
            if lines and lines[-1]:
                self._partial[prefix] = lines[-1]
            for line in lines[:-1]:
                if line:
                    self._sink(f"({prefix}) {line}")
