"""Typed GCS accessor client.

Reference: `src/ray/gcs/gcs_client/gcs_client.h:61` — raylets, workers
and the dashboard talk to GCS through typed accessors (NodeInfo, Actor,
InternalKV, ...) instead of raw RPC strings. Same layering here: any
process holding the head address builds a `GcsClient` and gets
namespaced accessors over the framed control-plane RPC (driver-side
callers can keep using the in-process `worker.gcs` GlobalState; this
client exists for NODE processes and external tools)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple, Union

from ray_tpu._private.rpc import RpcClient


class _KvAccessor:
    """InternalKV (reference `gcs_kv_manager.h`)."""

    def __init__(self, call):
        self._call = call

    def put(self, key: bytes, value: bytes, overwrite: bool = True,
            namespace: Optional[bytes] = None) -> bool:
        return self._call("gcs_kv_put", key=key, value=value,
                          overwrite=overwrite, namespace=namespace)

    def get(self, key: bytes,
            namespace: Optional[bytes] = None) -> Optional[bytes]:
        return self._call("gcs_kv_get", key=key, namespace=namespace)

    def delete(self, key: bytes,
               namespace: Optional[bytes] = None) -> None:
        self._call("gcs_kv_del", key=key, namespace=namespace)

    def keys(self, prefix: bytes = b"",
             namespace: Optional[bytes] = None) -> List[bytes]:
        return self._call("gcs_kv_keys", prefix=prefix,
                          namespace=namespace)


class _NodeAccessor:
    """Node directory (reference `GcsNodeManager` accessor)."""

    def __init__(self, call):
        self._call = call

    def list(self) -> List[dict]:
        return self._call("get_nodes")

    def alive(self) -> List[dict]:
        return [n for n in self.list() if n.get("alive", True)]


class _ActorAccessor:
    """Named-actor directory (reference `GcsActorManager` accessor)."""

    def __init__(self, call):
        self._call = call

    def list_named(self, all_namespaces: bool = False) -> List:
        return self._call("gcs_named_actors",
                          all_namespaces=all_namespaces)


class _PlacementGroupAccessor:
    def __init__(self, call):
        self._call = call

    def table(self) -> Dict[str, Any]:
        return self._call("gcs_pg_table")


class _EventAccessor:
    def __init__(self, call):
        self._call = call

    def list(self, limit: int = 200,
             source: Optional[str] = None) -> List[dict]:
        return self._call("gcs_events", limit=limit, source=source)


class GcsClient:
    def __init__(self, address: Union[str, Tuple[str, int]]):
        if isinstance(address, str):
            host, _, port = address.rpartition(":")
            address = (host, int(port))
        self._rpc = RpcClient.to(tuple(address))
        call = self._rpc.call
        self.kv = _KvAccessor(call)
        self.nodes = _NodeAccessor(call)
        self.actors = _ActorAccessor(call)
        self.placement_groups = _PlacementGroupAccessor(call)
        self.events = _EventAccessor(call)
