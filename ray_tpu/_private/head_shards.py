"""Multi-process head control plane: shard-by-key decision state.

The head's hot row state — object directory + sizes, in-flight
dispatches, lineage edges, per-(job, shape) lease registrations — is
partitioned by a STABLE key hash across N head shard processes
(``head_shards`` config; 1 = everything stays in the coordinator
process, today's behavior byte-for-byte). Reference shape: PAPER.md's
L4 — the GCS serves global metadata from its own service processes,
separate from the scheduling raylet.

Division of labor:

- the **coordinator** (the ClusterHead in the driver process) keeps
  node membership, the quota ledger, actor restart gates, and health —
  the cross-key singletons — plus an in-memory working copy of the row
  tables so its read paths never pay an RPC;
- each **shard process** owns the durable, authoritative copy of its
  key range: mutations stream in over one pipelined channel per shard
  (``rpc.CoalescingBatcher`` in front of ``rpc.PipelinedClient``, so
  frames route per-shard and coalesce per-shard), land in the shard's
  row tables, and group-commit into the shard's OWN
  ``SqliteStoreClient`` — durability and the loss bound are per-shard:
  a hard crash of one shard loses at most ITS open commit window,
  while its siblings' acked rows stay intact;
- lease registration is decision-bearing on the owning shard
  (``lease_register`` refuses to exceed the caller-declared cap), so a
  (job, shape) key's grants can never be tracked on two shards and a
  cap-1 key can never be double-granted — the raymc ``cross_shard``
  scenario proves both over every bounded interleaving and crash
  placement.

Failover: the coordinator's supervisor (`ShardRouter.poll`) restarts a
crashed shard from its sqlite db (acked rows reload); rows inside the
lost commit window re-register through the existing
report-returns-False path — the coordinator bumps its shard epoch, the
next ``report_resources`` from each node returns False once, and the
node re-registers and re-reports its actors and owned objects.
"""

from __future__ import annotations

import os
import pickle
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import perf_stats as _perf_stats
from ray_tpu._private import sanitize_hooks
from ray_tpu._private import wire
from ray_tpu._private.sched_state import stable_shard_of

# Tables persisted to the shard's sqlite store (group-committed).
# "lineage" rows are edges (oid -> creating task id), not specs: specs
# are code-bearing and coordinator-resident; a failed-over head
# re-learns them from node re-reports, the durable edge is what lets
# it tell "reconstructable" from "lost" meanwhile.
DURABLE_TABLES = ("objects", "sizes", "inflight", "lineage", "lease",
                  "actors")


def shard_of(key: bytes, n_shards: int) -> int:
    """Stable key -> shard map (crc32, NOT the salted builtin hash):
    the same key routes to the same shard across coordinator restarts,
    which is what lets a restarted head find durable rows where its
    predecessor left them."""
    return stable_shard_of(key, n_shards)


class HeadShardState:
    """One shard's decision core: row tables + its own group-commit
    window. Pure in-process object — the shard server wraps it behind
    an RpcServer; tests and the raymc ``cross_shard`` scenario drive it
    directly (every code path real, only the socket stubbed)."""

    def __init__(self, index: int, n_shards: int,
                 db_path: Optional[str] = None,
                 commit_interval_s: Optional[float] = None):
        self.index = index
        self.n_shards = n_shards
        self.tables: Dict[str, Dict[bytes, Any]] = {
            t: {} for t in DURABLE_TABLES}
        self._lock = threading.Lock()
        self.applied = 0
        self.store = None
        if db_path:
            from ray_tpu._private.gcs_storage import SqliteStoreClient

            self.store = SqliteStoreClient(
                db_path, commit_interval_s=commit_interval_s)
            self._load()

    def _load(self) -> None:
        """Reload the durable (acked) rows after a restart: everything
        a completed group commit covered; the open window at death is
        the documented loss bound."""
        for table in DURABLE_TABLES:
            rows = self.tables[table]
            for key, blob in self.store.get_all(table):
                rows[key] = pickle.loads(blob)

    def owns(self, key: bytes) -> bool:
        return shard_of(key, self.n_shards) == self.index

    # -- row mutations (the streamed per-shard frames) -------------------

    def apply(self, items: List[Any]) -> int:
        """Apply one coalesced mutation frame: items are
        ``wire.ShardRow`` messages (or bare ``(op, table, key, value)``
        tuples — the in-process harnesses use those) with op ``put`` |
        ``del``. Returns rows applied (the coordinator's batcher
        discards it; tests and the chaos harness assert on it)."""
        with self._lock:
            for item in items:
                if hasattr(item, "op"):
                    op, table, key, value = (item.op, item.table,
                                             item.key, item.value)
                else:
                    # A skewed peer can hand this rpc method ANY
                    # decodable value, not just row tuples — found by
                    # raywire fuzzing (TypeError unpacking a Request
                    # that arrived on the shard_apply seam).
                    try:
                        op, table, key, value = item
                    except (TypeError, ValueError):
                        raise wire.WireError(
                            "shard frame item is neither a ShardRow "
                            "nor an (op, table, key, value) row: "
                            f"{type(item).__name__}") from None
                sanitize_hooks.sched_point("headshard.apply")
                # Frames cross a version boundary during rolling
                # restarts, so every field a row names is validated
                # here and rejected TYPED: a skewed coordinator must
                # degrade to an error reply at the rpc boundary, not a
                # KeyError killing the shard's connection thread — and
                # an op this shard doesn't know must never fall into
                # the delete branch (silently destroying the row a
                # newer op meant to transform). Items before the bad
                # row stay applied; put/del are idempotent, so the
                # coordinator's retry after repair re-applies safely.
                rows = self.tables.get(table)
                if rows is None:
                    raise wire.WireError(
                        f"shard frame names unknown table {table!r}; "
                        f"known: {', '.join(DURABLE_TABLES)}")
                if not isinstance(key, bytes):
                    raise wire.WireError(
                        f"shard frame key must be bytes, got "
                        f"{type(key).__name__}")
                if op == "put":
                    rows[key] = value
                    if self.store is not None:
                        self.store.put(table, key, pickle.dumps(value))
                elif op == "del":
                    rows.pop(key, None)
                    if self.store is not None:
                        self.store.delete(table, key)
                else:
                    raise wire.WireError(
                        f"shard frame has unknown op {op!r} "
                        "(known: put, del)")
                self.applied += 1
        return len(items)

    # -- lease authority -------------------------------------------------

    def lease_register(self, key: bytes, node_id: str,
                       cap: int = 0) -> bool:
        """Record one lease grant for a (job, shape) key this shard
        owns. With ``cap > 0`` the shard is the admission authority:
        a grant past the cap is refused — the cross-shard single-grant
        invariant lives HERE, not in the caller's memory."""
        with self._lock:
            grants = list(self.tables["lease"].get(key, ()))
            if cap > 0 and len(grants) >= cap:
                return False
            grants.append(node_id)
            self.tables["lease"][key] = grants
            if self.store is not None:
                self.store.put("lease", key, pickle.dumps(grants))
        return True

    def lease_retire(self, key: bytes, node_id: str) -> bool:
        with self._lock:
            grants = list(self.tables["lease"].get(key, ()))
            if node_id not in grants:
                return False
            grants.remove(node_id)
            if grants:
                self.tables["lease"][key] = grants
                if self.store is not None:
                    self.store.put("lease", key, pickle.dumps(grants))
            else:
                self.tables["lease"].pop(key, None)
                if self.store is not None:
                    self.store.delete("lease", key)
        return True

    def lease_grants(self, key: bytes) -> List[str]:
        with self._lock:
            return list(self.tables["lease"].get(key, ()))

    # -- reads / folds ---------------------------------------------------

    def get(self, table: str, key: bytes) -> Any:
        with self._lock:
            return self.tables[table].get(key)

    def items(self, table: str) -> List[Tuple[bytes, Any]]:
        with self._lock:
            return list(self.tables[table].items())

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return {t: len(rows) for t, rows in self.tables.items()}

    def stats(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"index": self.index,
                               "applied": self.applied,
                               "rows": self.counts()}
        if self.store is not None:
            out["commits"] = self.store.commit_count
            out["commit_seconds_total"] = self.store.commit_seconds_total
            out["last_commit_s"] = self.store.last_commit_s
        return out

    def flush(self) -> None:
        if self.store is not None:
            self.store.flush()

    def close(self) -> None:
        if self.store is not None:
            self.store.flush()
            self.store.close()

    def crash(self) -> None:
        """Hard-death simulation: the open commit window rolls back
        (the per-shard loss bound the chaos test asserts)."""
        if self.store is not None:
            self.store.crash()


# -- shard server process ----------------------------------------------------


def serve(index: int, n_shards: int, db_path: str, port: int = 0,
          commit_interval_s: Optional[float] = None,
          ready_fd: Optional[int] = None):
    """Run one shard behind an RpcServer (the subprocess body; also
    callable in-process from tests). Prints/writes ``PORT <n>`` so the
    spawning coordinator can connect."""
    from ray_tpu._private.rpc import RpcServer

    state = HeadShardState(index, n_shards, db_path=db_path,
                           commit_interval_s=commit_interval_s)
    server = RpcServer({
        "shard_apply": lambda items: state.apply(items),
        "shard_get": lambda table, key: state.get(table, key),
        "shard_items": lambda table: state.items(table),
        "shard_stats": lambda: state.stats(),
        "shard_flush": lambda: (state.flush(), True)[1],
        "lease_register": lambda key, node_id, cap=0:
            state.lease_register(key, node_id, cap),
        "lease_retire": lambda key, node_id:
            state.lease_retire(key, node_id),
        "lease_grants": lambda key: state.lease_grants(key),
        "ping": lambda: "pong",
    }, port=port)
    line = f"PORT {server.address[1]}\n"
    if ready_fd is not None:
        os.write(ready_fd, line.encode())
        os.close(ready_fd)
    else:
        sys.stdout.write(line)
        sys.stdout.flush()
    return state, server


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(prog="ray_tpu head shard")
    parser.add_argument("--index", type=int, required=True)
    parser.add_argument("--shards", type=int, required=True)
    parser.add_argument("--db", required=True)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--commit-interval-s", type=float, default=None)
    args = parser.parse_args(argv)
    _state, server = serve(args.index, args.shards, args.db,
                           port=args.port,
                           commit_interval_s=args.commit_interval_s)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.shutdown()
    return 0


# -- coordinator-side router --------------------------------------------------


class _ShardChannel:
    """One shard's coordinator-side endpoints: the pipelined mutation
    stream (batcher -> PipelinedClient), the pooled sync socket for
    reads and lease decisions, and the subprocess handle."""

    def __init__(self, index: int, address, proc=None, db_path=""):
        from ray_tpu._private.rpc import (CoalescingBatcher,
                                          PipelinedClient, RpcClient)

        self.index = index
        self.address = tuple(address)
        self.proc = proc
        self.db_path = db_path
        self.alive = True
        self.pipe = PipelinedClient(self.address)
        self.batcher = CoalescingBatcher(
            self._send_frame, name=f"headshard-{index}",
            on_error=self._frame_error)
        self.client = RpcClient.dedicated(self.address)
        self.rpcs = _perf_stats.counter("head_shard_rpcs",
                                        {"shard": str(index)})
        self.depth = _perf_stats.dist("head_shard_queue_depth",
                                      {"shard": str(index)})

    def _send_frame(self, items) -> None:
        self.rpcs.inc()
        self.depth.record(self.batcher.backlog)
        self.pipe.send("shard_apply", items=items)

    def _frame_error(self, items, exc) -> None:
        # A dead shard's frames are the keys inside its loss window:
        # recovery is the re-registration path, not a retry queue (a
        # retry against the RESTARTED shard would race the node
        # re-reports that are already repopulating it).
        self.alive = False

    def call(self, method: str, **kwargs):
        self.rpcs.inc()
        return self.client.call(method, **kwargs)

    def close(self) -> None:
        for closer in (lambda: self.batcher.close(drain_timeout=2.0),
                       lambda: self.pipe.close(flush_timeout=2.0),
                       self.client.close):
            try:
                closer()
            except Exception:
                pass
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except Exception:
                self.proc.kill()


class ShardRouter:
    """Coordinator-side fan-out: stable key -> shard routing for the
    streamed mutation frames, sync calls for lease decisions and
    whole-table folds, and the supervisor that restarts crashed shard
    processes (`poll`)."""

    def __init__(self, n_shards: int, db_dir: str,
                 commit_interval_s: Optional[float] = None,
                 spawn: bool = True):
        self.n_shards = n_shards
        self.db_dir = db_dir
        self.commit_interval_s = commit_interval_s
        self.restarts = 0
        self._lock = threading.Lock()
        self.channels: List[_ShardChannel] = []
        if spawn:
            os.makedirs(db_dir, exist_ok=True)
            for i in range(n_shards):
                self.channels.append(self._spawn(i))

    def _spawn(self, index: int) -> _ShardChannel:
        db_path = os.path.join(self.db_dir, f"shard{index}.db")
        cmd = [sys.executable, "-m", "ray_tpu._private.head_shards",
               "--index", str(index), "--shards", str(self.n_shards),
               "--db", db_path]
        if self.commit_interval_s is not None:
            cmd += ["--commit-interval-s", str(self.commit_interval_s)]
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, text=True)
        line = proc.stdout.readline()
        if not line.startswith("PORT "):
            proc.kill()
            raise RuntimeError(
                f"head shard {index} failed to start: {line!r}")
        port = int(line.split()[1])
        return _ShardChannel(index, ("127.0.0.1", port), proc=proc,
                             db_path=db_path)

    def shard_of(self, key: bytes) -> int:
        return shard_of(key, self.n_shards)

    def channel_for(self, key: bytes) -> _ShardChannel:
        return self.channels[self.shard_of(key)]

    # -- streamed mutations ---------------------------------------------

    def put(self, table: str, key: bytes, value: Any) -> None:
        from ray_tpu._private import wire

        sanitize_hooks.sched_point("headshard.route")
        chan = self.channel_for(key)
        if not chan.alive:
            return  # keys in a dead shard's window ride re-registration
        try:
            chan.batcher.add(wire.ShardRow(op="put", table=table,
                                           key=key, value=value))
        except ConnectionError:
            chan.alive = False

    def delete(self, table: str, key: bytes) -> None:
        from ray_tpu._private import wire

        sanitize_hooks.sched_point("headshard.route")
        chan = self.channel_for(key)
        if not chan.alive:
            return
        try:
            chan.batcher.add(wire.ShardRow(op="del", table=table,
                                           key=key))
        except ConnectionError:
            chan.alive = False

    # -- sync decisions / reads -----------------------------------------

    def lease_register(self, key: bytes, node_id: str,
                       cap: int = 0) -> bool:
        """Register the grant with the key's owning shard. False when
        the shard refuses (cap) — and when the owning shard is DOWN:
        its key range stops granting until the supervisor restarts it,
        while every other shard's keys keep flowing (the failover
        semantics the chaos test pins)."""
        chan = self.channel_for(key)
        try:
            return bool(chan.call("lease_register", key=key,
                                  node_id=node_id, cap=cap))
        except Exception:
            chan.alive = False
            return False

    def lease_retire(self, key: bytes, node_id: str) -> bool:
        chan = self.channel_for(key)
        try:
            return bool(chan.call("lease_retire", key=key,
                                  node_id=node_id))
        except Exception:
            chan.alive = False
            return False

    def get(self, table: str, key: bytes) -> Any:
        chan = self.channel_for(key)
        self.flush_channel(chan)
        return chan.call("shard_get", table=table, key=key)

    def fold_items(self, table: str) -> List[Tuple[bytes, Any]]:
        """Whole-table view folded across every live shard (timeline /
        state merges). Flushes the streamed channels first so the fold
        observes everything added before the call."""
        out: List[Tuple[bytes, Any]] = []
        for chan in self.channels:
            if not chan.alive:
                continue
            try:
                self.flush_channel(chan)
                out.extend(chan.call("shard_items", table=table))
            except Exception:
                chan.alive = False
        return out

    def flush_channel(self, chan: _ShardChannel,
                      timeout: float = 10.0) -> None:
        if chan.alive:
            chan.batcher.flush(timeout)
            chan.pipe.flush(timeout)

    def flush(self, timeout: float = 10.0) -> bool:
        """Drain every shard's streamed channel AND its group-commit
        window: after this returns True, everything previously ``put``
        is crash-durable on its owning shard (the acked boundary the
        failover loss bound is measured against)."""
        ok = True
        for chan in self.channels:
            if not chan.alive:
                continue
            try:
                self.flush_channel(chan, timeout)
                chan.call("shard_flush")
            except Exception:
                chan.alive = False
                ok = False
        return ok

    def local_stats(self) -> List[Dict[str, Any]]:
        """Coordinator-side view only (no RPC): liveness + streamed
        backlog per shard. The healthz provider contract is "cheap and
        non-blocking", so verdicts read THIS, while the supervisor's
        periodic poll refreshes the full shard-side stats cache."""
        return [{"index": chan.index, "alive": chan.alive,
                 "backlog": chan.batcher.backlog if chan.alive else 0}
                for chan in self.channels]

    def stats(self) -> List[Dict[str, Any]]:
        out = []
        for chan in self.channels:
            row: Dict[str, Any] = {"index": chan.index,
                                   "alive": chan.alive,
                                   "backlog": chan.batcher.backlog
                                   if chan.alive else 0}
            if chan.alive:
                try:
                    row.update(chan.call("shard_stats"))
                except Exception:
                    chan.alive = False
                    row["alive"] = False
            out.append(row)
        return out

    # -- supervision -----------------------------------------------------

    def poll(self) -> List[int]:
        """Detect dead shard processes and restart them from their own
        durable db (acked rows reload; the open window at death is
        lost). Returns restarted indices — the coordinator bumps its
        shard epoch so nodes re-register and re-report the lost
        window's keys."""
        restarted = []
        with self._lock:
            for i, chan in enumerate(self.channels):
                dead = (chan.proc is not None
                        and chan.proc.poll() is not None)
                if not dead and chan.alive:
                    continue
                if not dead:
                    # Channel errored but the process lives: probe it
                    # before declaring death (a single frame error must
                    # not restart a healthy shard).
                    try:
                        chan.call("ping")  # raylint: disable=R2 -- _lock exists ONLY to make one supervision pass (probe + restart-decision + channel swap) atomic against another; routing paths never take it, so holding it across the probe is its entire job
                        chan.alive = True
                        continue
                    except Exception:
                        pass
                chan.close()
                self.channels[i] = self._spawn(i)  # raylint: disable=R2 -- see probe above: the respawned channel must be swapped in under the same supervision hold that condemned the old one, or two poll passes double-spawn shard i
                self.restarts += 1
                restarted.append(i)
        return restarted

    def kill_shard(self, index: int) -> None:
        """Hard-kill one shard process (chaos harness): SIGKILL, no
        flush — the open commit window dies with it."""
        chan = self.channels[index]
        if chan.proc is not None:
            chan.proc.kill()
            chan.proc.wait(timeout=10)
        chan.alive = False

    def close(self) -> None:
        # Graceful teardown drains streamed frames + each shard's open
        # group-commit window; crash exits never reach here (the loss
        # bound lives there, not on this path).
        self.flush()
        for chan in self.channels:
            chan.close()


class InprocRouter:
    """Transport-less router over in-process HeadShardStates: the raymc
    ``cross_shard`` scenario and unit tests drive the REAL routing +
    shard decision code with the sockets and subprocesses stubbed, so
    exhaustive exploration stays tractable."""

    def __init__(self, n_shards: int, states: Optional[list] = None):
        self.n_shards = n_shards
        self.shards = states if states is not None else [
            HeadShardState(i, n_shards) for i in range(n_shards)]

    def shard_of(self, key: bytes) -> int:
        return shard_of(key, self.n_shards)

    def put(self, table: str, key: bytes, value: Any) -> None:
        sanitize_hooks.sched_point("headshard.route")
        self.shards[self.shard_of(key)].apply(
            [("put", table, key, value)])

    def delete(self, table: str, key: bytes) -> None:
        sanitize_hooks.sched_point("headshard.route")
        self.shards[self.shard_of(key)].apply(
            [("del", table, key, None)])

    def lease_register(self, key: bytes, node_id: str,
                       cap: int = 0) -> bool:
        sanitize_hooks.sched_point("headshard.route")
        return self.shards[self.shard_of(key)].lease_register(
            key, node_id, cap)

    def lease_retire(self, key: bytes, node_id: str) -> bool:
        return self.shards[self.shard_of(key)].lease_retire(key, node_id)

    def get(self, table: str, key: bytes) -> Any:
        return self.shards[self.shard_of(key)].get(table, key)

    def fold_items(self, table: str) -> List[Tuple[bytes, Any]]:
        out: List[Tuple[bytes, Any]] = []
        for state in self.shards:
            out.extend(state.items(table))
        return out

    def flush(self, timeout: float = 10.0) -> bool:
        for state in self.shards:
            state.flush()
        return True

    def stats(self) -> List[Dict[str, Any]]:
        return [s.stats() for s in self.shards]

    def close(self) -> None:
        for state in self.shards:
            state.flush()
            state.close()


if __name__ == "__main__":
    sys.exit(main())
