"""Forked worker processes: crash isolation for task execution.

Role-equivalent to the reference's raylet WorkerPool
(`src/ray/raylet/worker_pool.h:156`): a pool of OS processes that execute
tasks so a segfaulting extension, an `os._exit`, or an OOM kill takes down
one worker — not the node (and its object store / actors / RPC server).

Differences from the reference, by design: workers here are *forked on
demand and kept warm* rather than pre-started per language/runtime-env
(fork is cheap on Linux and the parent already has the framework
imported), and the in-thread fast path remains the default — process
isolation is opted into per task/actor (``isolate_process=True``) or
globally via config, because a single-address-space hot path is the right
default for TPU-driving code (device handles don't survive fork).

Protocol: length-prefixed cloudpickle frames over a socketpair.
Parent sends ("call", fn, args, kwargs, runtime_env) and reads
("ok", value) | ("err", exception). A dead socket = a dead worker =
WorkerCrashedError, and the pool replaces the process.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time
from typing import Any, List

import cloudpickle

from ray_tpu import exceptions as exc
from ray_tpu._private import sanitize_hooks


def _send_frame(sock: socket.socket, obj: Any) -> None:
    payload = cloudpickle.dumps(obj)
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


# Sanity bound on one worker-pipe frame. The pipe is a parent↔child
# socketpair on one host, but a corrupted length prefix must fail typed
# (the callers' EOFError path) BEFORE the reader allocates what the
# 8-byte prefix claims — up to 16 EiB.
_MAX_FRAME_BYTES = 1 << 31


def _recv_frame(sock: socket.socket) -> Any:
    header = _recv_exact(sock, 8)
    (n,) = struct.unpack("<Q", header)
    if n > _MAX_FRAME_BYTES:
        raise EOFError(
            f"worker frame of {n} bytes exceeds the {_MAX_FRAME_BYTES}"
            " sanity bound (corrupt pipe?)")
    return pickle.loads(_recv_exact(sock, n))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise EOFError("worker process closed its pipe")
        buf.extend(chunk)
    return bytes(buf)


def _worker_main(sock: socket.socket) -> None:
    """Child process loop. Runs until the parent closes the socket."""
    os.environ["RAY_TPU_WORKER_PROCESS"] = "1"
    state: dict = {}
    while True:
        try:
            msg = _recv_frame(sock)
        except (EOFError, OSError):
            os._exit(0)
        kind = msg[0]
        try:
            if kind == "call":
                _, fn, args, kwargs, runtime_env = msg
                from ray_tpu._private.runtime_env import applied_runtime_env

                with applied_runtime_env(runtime_env):
                    result = fn(*args, **kwargs)
                _send_frame(sock, ("ok", result))
            elif kind == "init":  # isolated actor constructor
                _, cls, args, kwargs, runtime_env = msg
                from ray_tpu._private.runtime_env import applied_runtime_env

                with applied_runtime_env(runtime_env):
                    state["instance"] = cls(*args, **kwargs)
                _send_frame(sock, ("ok", None))
            elif kind == "method":  # isolated actor method call
                _, name, args, kwargs = msg
                result = getattr(state["instance"], name)(*args, **kwargs)
                _send_frame(sock, ("ok", result))
            elif kind == "exit":
                os._exit(0)
            else:
                _send_frame(sock, ("err", RuntimeError(f"bad op {kind!r}")))
        except BaseException as e:  # noqa: BLE001 - ship to parent
            try:
                _send_frame(sock, ("err", e))
            except Exception:
                # Unpicklable exception: ship a stand-in.
                _send_frame(sock, ("err", RuntimeError(
                    f"{type(e).__name__}: {e}")))


class WorkerProcess:
    """One worker process and its command socket.

    ``spawn=False`` (default) forks — cheap, shares the parent's warm
    imports. ``spawn=True`` execs a fresh interpreter — required when the
    worker must own pristine process-global state (e.g. a JAX
    ``jax.distributed`` rank: forked children inherit the parent's
    already-initialized XLA runtime, which cannot be re-wired)."""

    def __init__(self, spawn: bool = False):
        parent_sock, child_sock = socket.socketpair()
        if spawn:
            import subprocess
            import sys

            env = dict(os.environ)
            repo_root = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            env["PYTHONPATH"] = os.pathsep.join(
                [repo_root] + [p for p in sys.path if p])
            env["RAY_TPU_WORKER_FD"] = str(child_sock.fileno())
            self._popen = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu._private.worker_pool"],
                pass_fds=[child_sock.fileno()], env=env)
            child_sock.close()
            self.pid = self._popen.pid
        else:
            self._popen = None
            pid = os.fork()
            if pid == 0:
                # Child: drop the parent's end, serve, never return.
                parent_sock.close()
                try:
                    _worker_main(child_sock)
                finally:  # pragma: no cover - belt and braces
                    os._exit(0)
            child_sock.close()
            self.pid = pid
        self.sock = parent_sock
        self.alive = True
        # One in-flight request at a time: the frame protocol has no
        # request ids, so concurrent callers (an isolated actor with
        # max_concurrency > 1) must serialize here.
        self._req_lock = threading.Lock()

    def request(self, msg: Any) -> Any:
        """Send one command and wait for its reply; crash → raises
        WorkerCrashedError and marks the worker dead."""
        with self._req_lock:
            try:
                _send_frame(self.sock, msg)  # raylint: disable=R2 -- the frame protocol has no request ids: _req_lock IS the one-in-flight request/reply discipline for this worker socket
                kind, payload = _recv_frame(self.sock)  # raylint: disable=R2 -- see above: the reply must be read under the same hold that sent the request (frame ordering is the match)
            except (EOFError, OSError, BrokenPipeError):
                self.kill()  # raylint: disable=R2 -- the socket is already dead here; kill/reap of a SIGKILLed child returns promptly and racing requesters must observe the dead state, not interleave with it
                raise exc.WorkerCrashedError(
                    f"worker process {self.pid} died executing a task")
        if kind == "ok":
            return payload
        raise payload

    def kill(self) -> None:
        if not self.alive:
            return
        self.alive = False
        try:
            self.sock.close()
        except OSError:
            pass
        try:
            os.kill(self.pid, 9)
        except ProcessLookupError:
            pass
        self._reap()

    def _reap(self) -> None:
        if self._popen is not None:
            self._popen.wait()
            return
        try:
            os.waitpid(self.pid, 0)
        except ChildProcessError:
            pass


class WorkerPool:
    """Warm pool of forked workers for one-shot task execution, plus
    dedicated workers for isolated actors."""

    def __init__(self, max_idle: int = 4):
        self._idle: List[WorkerProcess] = []
        self._lock = threading.Lock()
        self._max_idle = max_idle
        self._closed = False
        # pid -> (proc, task_spec, start_time) for work currently
        # executing: the memory monitor's kill-policy input
        # (reference: the raylet's worker registry).
        self.active: dict = {}

    def run(self, fn, args, kwargs, runtime_env=None,
            spawn: bool = False, meta=None) -> Any:
        """Execute fn in a worker process. Raises the task's own
        exception on user error, WorkerCrashedError if the process died
        (or was OOM-killed by the memory monitor). ``spawn=True`` uses a
        one-shot fresh interpreter (never pooled — pristine process
        globals are the whole point). ``meta`` (the TaskSpec) feeds the
        worker-killing policy."""
        sanitize_hooks.sched_point("workerpool.run")
        worker = WorkerProcess(spawn=True) if spawn else self._checkout()
        with self._lock:
            self.active[worker.pid] = (worker, meta, time.time())
        try:
            result = worker.request(("call", fn, args, kwargs, runtime_env))
        except BaseException:
            # Deregister BEFORE check-in: once the worker is back in the
            # pool another task may claim it and register the same pid.
            with self._lock:
                self.active.pop(worker.pid, None)
            if spawn:
                worker.kill()
            elif worker.alive:
                self._checkin(worker)
            raise
        with self._lock:
            self.active.pop(worker.pid, None)
        if spawn:
            worker.kill()
        else:
            self._checkin(worker)
        return result

    def dedicated(self, spawn: bool = False, meta=None) -> WorkerProcess:
        """A worker owned by the caller (isolated actors); never pooled
        but registered in `active` so the memory-pressure kill policy can
        see it (an OOM'd isolated actor dies and restarts via
        max_restarts instead of the kernel killing the node)."""
        worker = WorkerProcess(spawn=spawn)
        with self._lock:
            self.active[worker.pid] = (worker, meta, time.time())
        return worker

    def release_dedicated(self, worker: WorkerProcess) -> None:
        with self._lock:
            self.active.pop(worker.pid, None)
        worker.kill()

    def _checkout(self) -> WorkerProcess:
        with self._lock:
            if self._idle:
                return self._idle.pop()
        return WorkerProcess()

    def _checkin(self, worker: WorkerProcess) -> None:
        with self._lock:
            if not self._closed and worker.alive and \
                    len(self._idle) < self._max_idle:
                self._idle.append(worker)
                return
        worker.kill()

    def shutdown(self) -> None:
        with self._lock:
            self._closed = True
            idle, self._idle = self._idle, []
        for worker in idle:
            worker.kill()


if __name__ == "__main__":
    # Spawned-worker entry: serve the command socket handed down via fd.
    _fd = int(os.environ["RAY_TPU_WORKER_FD"])
    _worker_main(socket.socket(fileno=_fd))
