"""Forked worker processes: crash isolation for task execution.

Role-equivalent to the reference's raylet WorkerPool
(`src/ray/raylet/worker_pool.h:156`): a pool of OS processes that execute
tasks so a segfaulting extension, an `os._exit`, or an OOM kill takes down
one worker — not the node (and its object store / actors / RPC server).

Differences from the reference, by design: workers here are *forked on
demand and kept warm* rather than pre-started per language/runtime-env
(fork is cheap on Linux and the parent already has the framework
imported), and the in-thread fast path remains the default — process
isolation is opted into per task/actor (``isolate_process=True``) or
globally via config, because a single-address-space hot path is the right
default for TPU-driving code (device handles don't survive fork).

Protocol: length-prefixed cloudpickle frames over a socketpair.
Parent sends ("call", fn, args, kwargs, runtime_env) and reads
("ok", value) | ("err", exception). A dead socket = a dead worker =
WorkerCrashedError, and the pool replaces the process.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
from typing import Any, List, Optional

import cloudpickle

from ray_tpu import exceptions as exc


def _send_frame(sock: socket.socket, obj: Any) -> None:
    payload = cloudpickle.dumps(obj)
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> Any:
    header = _recv_exact(sock, 8)
    (n,) = struct.unpack("<Q", header)
    return pickle.loads(_recv_exact(sock, n))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise EOFError("worker process closed its pipe")
        buf.extend(chunk)
    return bytes(buf)


def _worker_main(sock: socket.socket) -> None:
    """Child process loop. Runs until the parent closes the socket."""
    os.environ["RAY_TPU_WORKER_PROCESS"] = "1"
    state: dict = {}
    while True:
        try:
            msg = _recv_frame(sock)
        except (EOFError, OSError):
            os._exit(0)
        kind = msg[0]
        try:
            if kind == "call":
                _, fn, args, kwargs, runtime_env = msg
                from ray_tpu._private.runtime_env import applied_runtime_env

                with applied_runtime_env(runtime_env):
                    result = fn(*args, **kwargs)
                _send_frame(sock, ("ok", result))
            elif kind == "init":  # isolated actor constructor
                _, cls, args, kwargs, runtime_env = msg
                from ray_tpu._private.runtime_env import applied_runtime_env

                with applied_runtime_env(runtime_env):
                    state["instance"] = cls(*args, **kwargs)
                _send_frame(sock, ("ok", None))
            elif kind == "method":  # isolated actor method call
                _, name, args, kwargs = msg
                result = getattr(state["instance"], name)(*args, **kwargs)
                _send_frame(sock, ("ok", result))
            elif kind == "exit":
                os._exit(0)
            else:
                _send_frame(sock, ("err", RuntimeError(f"bad op {kind!r}")))
        except BaseException as e:  # noqa: BLE001 - ship to parent
            try:
                _send_frame(sock, ("err", e))
            except Exception:
                # Unpicklable exception: ship a stand-in.
                _send_frame(sock, ("err", RuntimeError(
                    f"{type(e).__name__}: {e}")))


class WorkerProcess:
    """One forked worker and its command socket."""

    def __init__(self):
        parent_sock, child_sock = socket.socketpair()
        pid = os.fork()
        if pid == 0:
            # Child: drop the parent's end, serve, never return.
            parent_sock.close()
            try:
                _worker_main(child_sock)
            finally:  # pragma: no cover - belt and braces
                os._exit(0)
        child_sock.close()
        self.pid = pid
        self.sock = parent_sock
        self.alive = True
        # One in-flight request at a time: the frame protocol has no
        # request ids, so concurrent callers (an isolated actor with
        # max_concurrency > 1) must serialize here.
        self._req_lock = threading.Lock()

    def request(self, msg: Any) -> Any:
        """Send one command and wait for its reply; crash → raises
        WorkerCrashedError and marks the worker dead."""
        with self._req_lock:
            try:
                _send_frame(self.sock, msg)
                kind, payload = _recv_frame(self.sock)
            except (EOFError, OSError, BrokenPipeError):
                self.kill()
                raise exc.WorkerCrashedError(
                    f"worker process {self.pid} died executing a task")
        if kind == "ok":
            return payload
        raise payload

    def kill(self) -> None:
        if not self.alive:
            return
        self.alive = False
        try:
            self.sock.close()
        except OSError:
            pass
        try:
            os.kill(self.pid, 9)
        except ProcessLookupError:
            pass
        self._reap()

    def _reap(self) -> None:
        try:
            os.waitpid(self.pid, 0)
        except ChildProcessError:
            pass


class WorkerPool:
    """Warm pool of forked workers for one-shot task execution, plus
    dedicated workers for isolated actors."""

    def __init__(self, max_idle: int = 4):
        self._idle: List[WorkerProcess] = []
        self._lock = threading.Lock()
        self._max_idle = max_idle
        self._closed = False

    def run(self, fn, args, kwargs, runtime_env=None) -> Any:
        """Execute fn in a pooled worker process. Raises the task's own
        exception on user error, WorkerCrashedError if the process died."""
        worker = self._checkout()
        try:
            result = worker.request(("call", fn, args, kwargs, runtime_env))
        except BaseException:
            if worker.alive:
                self._checkin(worker)
            raise
        self._checkin(worker)
        return result

    def dedicated(self) -> WorkerProcess:
        """A worker owned by the caller (isolated actors); never pooled."""
        return WorkerProcess()

    def _checkout(self) -> WorkerProcess:
        with self._lock:
            if self._idle:
                return self._idle.pop()
        return WorkerProcess()

    def _checkin(self, worker: WorkerProcess) -> None:
        with self._lock:
            if not self._closed and worker.alive and \
                    len(self._idle) < self._max_idle:
                self._idle.append(worker)
                return
        worker.kill()

    def shutdown(self) -> None:
        with self._lock:
            self._closed = True
            idle, self._idle = self._idle, []
        for worker in idle:
            worker.kill()
