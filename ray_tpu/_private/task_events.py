"""Task event buffer: the observability substrate.

Role-equivalent to the reference's `TaskEventBuffer`
(`core_worker/task_event_buffer.h:188`) feeding GcsTaskManager: every task
execution records state transitions + timing here; the state API
(`ray_tpu.experimental.state`) queries it and `ray_tpu.timeline()` dumps
Chrome traces from it (reference `_private/state.py:435`).

Cluster mode: worker-node buffers ship their deltas to the head's
aggregator (`_private/obs_plane.py`) so timeline/tracing/state views are
cluster-wide. Shipping drains ``drain_updates`` — a bounded dirty set,
not a full-buffer scan — off the execution hot path.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, fields
from typing import Any, Dict, List, Optional

from ray_tpu._private.task_spec import trace_id_of as _trace_id_of


@dataclass
class TaskEvent:
    task_id: str
    name: str
    kind: str            # NORMAL_TASK | ACTOR_CREATION | ACTOR_TASK
    state: str           # RUNNING | FINISHED | FAILED
    start_s: float = 0.0
    end_s: Optional[float] = None
    node_id: str = ""
    worker: str = ""
    error: str = ""
    actor_id: Optional[str] = None
    # Span linkage: the task's own id is its span id.
    trace_id: str = ""
    parent_span_id: str = ""
    # Job/tenant tag carried by the spec ("" = untagged): the per-job
    # attribution key for state.job_summary(), the job-tagged metric
    # series, and timeline filtering.
    job_id: str = ""

    def duration_s(self) -> Optional[float]:
        if self.end_s is None:
            return None
        return self.end_s - self.start_s

    def to_dict(self) -> Dict[str, Any]:
        """Wire-friendly plain dict (str/float/None only — no pickle
        needed on the shipping channel)."""
        return {f.name: getattr(self, f.name) for f in fields(self)}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TaskEvent":
        known = {f.name for f in fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


def chrome_trace_events(events) -> List[dict]:
    """Chrome tracing format (`chrome://tracing` / Perfetto) for any
    event iterable — the buffer's own dump and the head's cluster-wide
    ``timeline()`` share this formatter."""
    out = []
    now = time.time()
    for ev in events:
        end = ev.end_s or now
        out.append({
            "name": ev.name,
            "cat": ev.kind.lower(),
            "ph": "X",
            "ts": ev.start_s * 1e6,
            "dur": (end - ev.start_s) * 1e6,
            "pid": ev.node_id[:8],
            "tid": ev.worker,
            "args": {"task_id": ev.task_id, "state": ev.state,
                     **({"job": ev.job_id} if ev.job_id else {}),
                     **({"error": ev.error} if ev.error else {})},
        })
    return out


class TaskEventBuffer:
    def __init__(self, max_events: int = 100_000):
        self._lock = threading.Lock()
        self._events: "collections.OrderedDict[str, TaskEvent]" = \
            collections.OrderedDict()
        self._max = max_events
        # task_ids updated since the last drain — THE shipping cursor
        # (drain_updates consumes it; a finish re-marks its task so the
        # terminal state ships too); bounded by _max through the same
        # eviction sweep.
        self._dirty: "collections.OrderedDict[str, None]" = \
            collections.OrderedDict()
        # Bumped on every insert/update: a cheap change fingerprint so
        # per-scrape aggregations (the job-metric fold) can skip their
        # full-buffer walk when nothing moved between scrapes.
        self._mutations = 0

    @property
    def capacity(self) -> int:
        return self._max

    @property
    def mutation_seq(self) -> int:
        return self._mutations

    def task_started(self, spec, node_id, worker_name: str) -> None:
        ev = TaskEvent(
            task_id=spec.task_id.hex(), name=spec.name,
            kind=spec.kind.name, state="RUNNING",
            start_s=time.time(), node_id=node_id.hex(),
            worker=worker_name,
            actor_id=spec.actor_id.hex() if spec.actor_id else None,
            trace_id=_trace_id_of(spec),
            parent_span_id=(spec.trace_parent[1] if spec.trace_parent
                            else ""),
            job_id=spec.job_id or "")
        with self._lock:
            self._mutations += 1
            self._events[ev.task_id] = ev
            self._dirty[ev.task_id] = None
            while len(self._events) > self._max:
                evicted, _ = self._events.popitem(last=False)
                self._dirty.pop(evicted, None)

    def task_finished(self, spec, error: Optional[str] = None) -> None:
        with self._lock:
            ev = self._events.get(spec.task_id.hex())
            if ev is None:
                return
            self._mutations += 1
            ev.end_s = time.time()
            ev.state = "FAILED" if error else "FINISHED"
            ev.error = error or ""
            self._dirty[ev.task_id] = None

    def record_event(self, ev: TaskEvent) -> None:
        """Insert a fully-formed event (runtime incidents that are not a
        task execution — e.g. the memory monitor's worker-kill
        decisions — use this so they show up in timeline()/state views
        and ship to the head like any task event)."""
        with self._lock:
            self._mutations += 1
            self._events[ev.task_id] = ev
            self._dirty[ev.task_id] = None
            while len(self._events) > self._max:
                evicted, _ = self._events.popitem(last=False)
                self._dirty.pop(evicted, None)

    def list_events(self, limit: int = 10_000) -> List[TaskEvent]:
        with self._lock:
            return list(self._events.values())[-limit:]

    def snapshot(self, limit: Optional[int] = None) -> List[TaskEvent]:
        """The public full-buffer view: every recorded event (or the
        most recent ``limit``), oldest first. Exporters that must not
        truncate (span export would drop trace roots out from under
        their children) use this instead of reaching into the buffer's
        internals."""
        with self._lock:
            events = list(self._events.values())
        return events if limit is None else events[-limit:]

    def drain_updates(self, limit: int = 2000) -> List[Dict[str, Any]]:
        """Up to ``limit`` event dicts updated since the previous drain
        (the node→head shipping delta). Bounded: anything beyond the
        limit stays dirty for the next cycle, so one burst can never
        produce an unbounded frame."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            while self._dirty and len(out) < limit:
                task_id, _ = self._dirty.popitem(last=False)
                ev = self._events.get(task_id)
                if ev is not None:
                    out.append(ev.to_dict())
        return out

    def remark_dirty(self, task_ids) -> None:
        """Put drained task ids back on the shipping cursor (the
        shipper's RPC failed AFTER the drain — without this, events
        completed in that window would silently never reach the head)."""
        with self._lock:
            for task_id in task_ids:
                if task_id in self._events:
                    self._dirty[task_id] = None

    def dirty_count(self) -> int:
        with self._lock:
            return len(self._dirty)

    def chrome_trace(self) -> List[dict]:
        """Chrome tracing format (`chrome://tracing` / Perfetto)."""
        return chrome_trace_events(self.snapshot())
