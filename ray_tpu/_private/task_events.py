"""Task event buffer: the observability substrate.

Role-equivalent to the reference's `TaskEventBuffer`
(`core_worker/task_event_buffer.h:188`) feeding GcsTaskManager: every task
execution records state transitions + timing here; the state API
(`ray_tpu.experimental.state`) queries it and `ray_tpu.timeline()` dumps
Chrome traces from it (reference `_private/state.py:435`).
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu._private.task_spec import trace_id_of as _trace_id_of


@dataclass
class TaskEvent:
    task_id: str
    name: str
    kind: str            # NORMAL_TASK | ACTOR_CREATION | ACTOR_TASK
    state: str           # RUNNING | FINISHED | FAILED
    start_s: float = 0.0
    end_s: Optional[float] = None
    node_id: str = ""
    worker: str = ""
    error: str = ""
    actor_id: Optional[str] = None
    # Span linkage: the task's own id is its span id.
    trace_id: str = ""
    parent_span_id: str = ""

    def duration_s(self) -> Optional[float]:
        if self.end_s is None:
            return None
        return self.end_s - self.start_s


class TaskEventBuffer:
    def __init__(self, max_events: int = 100_000):
        self._lock = threading.Lock()
        self._events: "collections.OrderedDict[str, TaskEvent]" = \
            collections.OrderedDict()
        self._max = max_events

    def task_started(self, spec, node_id, worker_name: str) -> None:
        ev = TaskEvent(
            task_id=spec.task_id.hex(), name=spec.name,
            kind=spec.kind.name, state="RUNNING",
            start_s=time.time(), node_id=node_id.hex(),
            worker=worker_name,
            actor_id=spec.actor_id.hex() if spec.actor_id else None,
            trace_id=_trace_id_of(spec),
            parent_span_id=(spec.trace_parent[1] if spec.trace_parent
                            else ""))
        with self._lock:
            self._events[ev.task_id] = ev
            while len(self._events) > self._max:
                self._events.popitem(last=False)

    def task_finished(self, spec, error: Optional[str] = None) -> None:
        with self._lock:
            ev = self._events.get(spec.task_id.hex())
            if ev is None:
                return
            ev.end_s = time.time()
            ev.state = "FAILED" if error else "FINISHED"
            ev.error = error or ""

    def list_events(self, limit: int = 10_000) -> List[TaskEvent]:
        with self._lock:
            return list(self._events.values())[-limit:]

    def chrome_trace(self) -> List[dict]:
        """Chrome tracing format (`chrome://tracing` / Perfetto)."""
        out = []
        for ev in self.list_events():
            end = ev.end_s or time.time()
            out.append({
                "name": ev.name,
                "cat": ev.kind.lower(),
                "ph": "X",
                "ts": ev.start_s * 1e6,
                "dur": (end - ev.start_s) * 1e6,
                "pid": ev.node_id[:8],
                "tid": ev.worker,
                "args": {"task_id": ev.task_id, "state": ev.state,
                         **({"error": ev.error} if ev.error else {})},
            })
        return out
