"""In-process execution backend: resource-aware scheduler + worker threads.

This is the single-node substrate the public API runs on by default. It
reproduces the *semantics* of the reference's raylet + core-worker pair —
dependency-gated dispatch (``LocalTaskManager``, reference
``src/ray/raylet/local_task_manager.cc:91``), resource accounting, ordered
per-actor queues (``direct_actor_task_submitter.h``), blocked-worker CPU
release (the block/unblock notifications in ``raylet_client.h``) — with
threads in one process instead of forked worker processes. The multiprocess
node (``cluster.py``) layers real process isolation and the shared-memory
object store on the same TaskSpec/scheduling interfaces.
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import queue
import threading
from typing import Any, Dict, Optional

from time import monotonic as _monotonic

from ray_tpu import exceptions as exc
from ray_tpu._private import critical_path as _critical_path
from ray_tpu._private import perf_stats as _perf_stats
from ray_tpu._private import sched_state, tenancy
from ray_tpu._private.ids import ActorID, NodeID, ObjectID
from ray_tpu._private.resources import ResourceSet, spec_milli, to_milli
from ray_tpu._private.task_spec import (
    DefaultSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    QueuedTaskHeader,
    TaskKind,
    TaskSpec,
)
from ray_tpu._private.task_spec import trace_id_of as _trace_id_of

logger = logging.getLogger(__name__)

# Submit→execution-start latency (normal tasks: scheduler queue +
# dispatch; actor tasks: mailbox wait) — module-level so both execute
# paths share one distribution.
_SCHED_LATENCY = _perf_stats.latency("sched_submit_to_start_seconds")
# Compact-queue observability (ray_tpu_sched_* after the runtime-
# metrics fold): header-queued submissions + their approximate queued
# footprint, and the header→spec materialization cost at dispatch.
_HEADERS_QUEUED = _perf_stats.counter("sched_headers_queued")
_HEADER_BYTES = _perf_stats.counter("sched_queued_header_bytes")
_MATERIALIZE = _perf_stats.latency("sched_materialize_seconds")


class _BlockedState(threading.local):
    """Per-thread record of resources released while blocked in get()."""

    def __init__(self):
        self.stack = []


# Actor-death observers: modules holding per-actor registries keyed by
# actor id (util.collective's group tables) register a cleanup callable
# here so a dying actor's rows don't outlive it. Process-wide, called
# with the ActorID from every local death path; unregister provided
# (reset-capable).
_ACTOR_DEATH_HOOKS: list = []


def register_actor_death_hook(fn) -> None:
    if fn not in _ACTOR_DEATH_HOOKS:
        _ACTOR_DEATH_HOOKS.append(fn)


def unregister_actor_death_hook(fn) -> None:
    if fn in _ACTOR_DEATH_HOOKS:
        _ACTOR_DEATH_HOOKS.remove(fn)


def _fire_actor_death_hooks(actor_id: "ActorID") -> None:
    for fn in list(_ACTOR_DEATH_HOOKS):
        try:
            fn(actor_id)
        except Exception:
            pass


class ActorState:
    ALIVE = "ALIVE"
    DEAD = "DEAD"
    RESTARTING = "RESTARTING"
    PENDING = "PENDING_CREATION"


class _Actor:
    """Server side of one actor: mailbox + executor thread(s)."""

    def __init__(self, backend: "LocalBackend", spec: TaskSpec):
        self.backend = backend
        self.spec = spec
        self.actor_id: ActorID = spec.actor_id
        self.state = ActorState.PENDING
        self.instance: Any = None
        self.mailbox: "queue.Queue[Optional[TaskSpec]]" = queue.Queue()
        self.death_cause = ""
        self.num_restarts = 0
        # Guards state transitions vs. mailbox puts (kill/submit race),
        # and — in pool mode — the activation slot count.
        self.mb_lock = threading.Lock()
        # Pool mode: serializes construction against a (theoretical)
        # concurrent second activation; never held during serving.
        self.ctor_lock = threading.Lock()
        self.is_async = bool(sched_state.class_is_async(spec.func))
        # Shared-executor serving (sched_actor_executor_pool): sync
        # in-process actors are drained by the backend's grow-on-demand
        # executor pool instead of dedicated threads, so 10k actors
        # cost 10k mailboxes and ZERO standing threads. max_concurrency
        # bounds CONCURRENT drain passes per actor (multi-slot —
        # sched_actor_pool_multislot; serve replicas declare
        # max_concurrency>1 and used to pin that many standing threads
        # each); at max_concurrency=1 a single activation at a time
        # preserves strict mailbox order exactly as before. Async /
        # process-isolated actors keep the dedicated-thread path.
        from ray_tpu._private.config import ray_config

        self.pool_mode = bool(
            ray_config.sched_actor_executor_pool and not self.is_async
            and not spec.isolate_process
            and (spec.max_concurrency <= 1
                 or ray_config.sched_actor_pool_multislot))
        # Pool mode: drain passes (slots) currently scheduled/running,
        # bounded by max_slots. Guarded by mb_lock.
        self.max_slots = max(1, spec.max_concurrency) \
            if self.pool_mode else 1
        self._active_count = 0
        self._threads: list[threading.Thread] = []
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # Dedicated forked worker when spec.isolate_process is set.
        self._proc = None

    def start(self):
        if self.pool_mode:
            # Constructor + queued calls run as one drain pass on the
            # shared executor pool (no per-actor thread).
            self.backend._activate_actor(self)
            return
        n = max(1, self.spec.max_concurrency) if not self.is_async else 1
        for i in range(n):
            t = threading.Thread(
                target=self._run_loop, name=f"actor-{self.actor_id.hex()[:8]}-{i}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)

    def _construct(self) -> bool:
        """Run the constructor; returns True on success. Pushes task
        context (so tasks submitted from __init__ join the caller's
        trace) and records a construction span."""
        spec = self.spec
        ctx = self.backend.worker.task_context
        events = self.backend.worker.task_events
        ctx.push(task_spec=spec, node_id=self.backend.node_id, pool=None,
                 request=None)
        events.task_started(spec, self.backend.node_id,
                            threading.current_thread().name)
        try:
            # Constructor args resolve top-level ObjectRefs exactly like
            # method args (reference: core_worker actor creation task).
            args, kwargs = self.backend.worker.resolve_args(spec)
            if spec.isolate_process:
                # The instance lives in a dedicated worker process; the
                # node only holds the command socket. "spawn" execs a
                # fresh interpreter (pristine process globals — needed
                # for jax.distributed ranks); True forks.
                self._proc = self.backend.worker_pool.dedicated(
                    spawn=spec.isolate_process == "spawn", meta=spec)
                self._proc.request(("init", spec.func, args,
                                    kwargs, spec.runtime_env))
            else:
                self.instance = spec.func(*args, **kwargs)
            self.state = ActorState.ALIVE
            self.backend.worker.store_task_outputs(spec, [None])
            events.task_finished(spec)
            return True
        except BaseException as e:  # noqa: BLE001 - constructor error kills actor
            self.state = ActorState.DEAD
            self.death_cause = f"constructor raised {type(e).__name__}: {e}"
            err = exc.TaskError(e, spec.describe())
            self.backend.worker.store_task_outputs(spec, None, error=err)
            events.task_finished(spec, error=f"{type(e).__name__}: {e}")
            self.backend._on_actor_death(self, err)
            return False
        finally:
            ctx.pop()

    def _run_loop(self):
        # Only the first thread constructs; others wait until alive.
        is_primary = threading.current_thread() is self._threads[0] if self._threads else True
        if is_primary or self.state == ActorState.PENDING:
            with self.backend._actor_ctor_lock:
                if self.state == ActorState.PENDING:
                    if not self._construct():
                        return
        if self.is_async:
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)
        while True:
            try:
                item = self.mailbox.get(timeout=0.5)
            except queue.Empty:
                # Sentinel counting can undercount when a kill races
                # start() mid-spawn; the periodic state check guarantees
                # every executor thread exits after death regardless.
                if self.state == ActorState.DEAD:
                    return
                continue
            if item is None:
                return
            if self.state == ActorState.DEAD:
                self.backend.worker.store_task_outputs(
                    item, None,
                    error=exc.ActorDiedError(self.actor_id.hex()[:8], self.death_cause),
                )
                continue
            self.backend._execute_actor_task(self, item)

    def stop(self, cause: str = "killed") -> list:
        """Transition to DEAD; returns specs that were still queued.

        Under mb_lock so no submit can slip a spec in between the drain and
        the shutdown sentinels (which would leave its caller hanging).
        """
        with self.mb_lock:
            already_dead = self.state == ActorState.DEAD
            self.state = ActorState.DEAD
            self.death_cause = self.death_cause if already_dead else cause
            drained = []
            try:
                while True:
                    item = self.mailbox.get_nowait()
                    if item is not None:
                        drained.append(item)
            except queue.Empty:
                pass
            if not already_dead and not self.pool_mode:
                # Wake every dedicated executor thread (pool-mode
                # actors have none to wake: an active drain pass
                # observes DEAD at its next item and retires).
                for _ in (self._threads or [None]):
                    self.mailbox.put(None)
        # Abrupt-stop hook, OUTSIDE mb_lock (it may take the instance's
        # own locks): an instance that spawned background threads or
        # parked waiters has no other way to learn it was killed — a
        # real process death would reap them, but this runtime's actors
        # are threads, so an un-hooked kill leaks every one of them
        # (the leak sanitizer caught the serve controller's reconciler
        # and long-poll waiters surviving crash-simulation kills).
        if not already_dead:
            hook = getattr(self.instance, "_on_actor_stop", None)
            if hook is not None:
                try:
                    hook()
                except Exception:
                    pass
        return drained


class LocalBackend:
    """One node's scheduler and execution engine, in-process."""

    def __init__(self, worker, resources: Dict[str, float],
                 node_id: Optional[NodeID] = None):
        self.worker = worker
        self.node_id = node_id or NodeID.from_random()
        self.resources = ResourceSet(resources)
        # Dependency-parked work: a pure decision core with exactly-
        # once handoff between the ready path and the death sweep
        # (raymc dep_sweep scenario proves the claim protocol; ROADMAP
        # FT gap d). Items are queued forms — headers or full specs.
        self._deps = sched_state.DepTable()
        # Demand of dep-parked work, charged at park and released at
        # claim (ready or sweep). NOT part of the backlog signal (the
        # work is not runnable yet) but head-local placement of
        # lifetime-pinned creations must see it — a dep-blocked
        # creation burst otherwise over-lands on the head and the
        # overflow parks forever once the deps resolve.
        self._dep_demand = sched_state.PendingCounter()
        # Runnable queue: per-job virtual-time WFQ when tenancy
        # enforcement + weights are configured, byte-identical FIFO
        # otherwise (one class). Same put/get/get_nowait surface as the
        # queue.Queue it replaces.
        self._ready = tenancy.FairTaskQueue()
        # Per-job quota ledger (tenancy enforcement): queued-task
        # ceiling at admission, CPU-slot gate at dispatch. One ledger
        # per head process — the cluster mixin shares it through
        # __getattr__ delegation so a job's usage is one number whether
        # its tasks run here or ride a lease. Node processes disable
        # theirs (the head already enforced at grant).
        self.quota_ledger = tenancy.QuotaLedger()
        self._waiting_for_resources: list[TaskSpec] = []
        # Incremental queued-demand accounting (reference: raylet
        # backlog) under its own small lock — the submit hot path's
        # add/remove never contends with the dep table or the parked
        # list. Scanning the ready queue per submission made the
        # local-fit check O(queue) -> O(n^2) over a fan-out burst.
        self._pending = sched_state.PendingCounter()
        # Grow-on-demand executor pool for normal tasks (see _launch).
        self._exec_q: "queue.Queue" = queue.Queue()
        self._exec_idle = 0
        self._exec_lock = threading.Lock()
        # Materialization-latency sampling tick (1/32; benign race —
        # a lost increment only shifts which dispatch gets timed).
        self._mat_tick = 0
        # Every executor thread ever spawned (pruned of dead ones at
        # spawn): shutdown() wakes each blocked get() with a None
        # sentinel — without it an idle executor sits out its full 10s
        # poll after shutdown, which the leak sanitizer rightly calls a
        # leaked thread.
        self._exec_threads: list[threading.Thread] = []
        self._actors: dict[ActorID, _Actor] = {}
        self._cancelled: set[bytes] = set()
        self._lock = threading.Lock()
        self._actor_ctor_lock = threading.Lock()
        self._blocked = _BlockedState()
        self._shutdown = threading.Event()
        # Per-bundle resource sets for placement groups: (pg_id, index) -> ResourceSet
        self.bundle_resources: dict[tuple, ResourceSet] = {}
        # Forked-worker pool for isolate_process tasks/actors, created on
        # first use (reference: worker_pool.h:156).
        self._worker_pool = None
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="raylet-dispatch", daemon=True
        )
        self._dispatcher.start()

    @property
    def worker_pool(self):
        if self._worker_pool is None:
            from ray_tpu._private.memory_monitor import MemoryMonitor
            from ray_tpu._private.worker_pool import WorkerPool

            self._worker_pool = WorkerPool()
            # Worker killing under memory pressure only makes sense once
            # killable (process-isolated) work exists.
            self._memory_monitor = MemoryMonitor(self)
            self._memory_monitor.start()
        return self._worker_pool

    # ------------------------------------------------------------------
    # Submission
    # ------------------------------------------------------------------

    def submit(self, spec: TaskSpec) -> None:
        # Scheduling-latency stamp (submit→start, measured at execution
        # start): one monotonic read + attribute write — cheap enough
        # for the submit hot path, gated for the A/B overhead bench.
        if _perf_stats.ENABLED:
            spec._submit_monotonic = _monotonic()
        if spec.kind == TaskKind.ACTOR_TASK:
            self._submit_actor_task(spec)
            return
        # Tenancy admission: a job at its queued-task ceiling is
        # rejected HERE, with a typed error, before the spec costs the
        # scheduler anything (idempotent per spec — cluster-mixin
        # admission and dep-park resubmits never double-charge).
        reason = self.quota_ledger.note_queued(spec)
        if reason is not None:
            self.worker.store_task_outputs(
                spec, None, error=exc.JobQuotaExceededError(
                    spec.job_id or "", reason))
            return
        if spec.kind == TaskKind.ACTOR_CREATION:
            existing = self._actors.get(spec.actor_id)
            if existing is not None and \
                    existing.state != ActorState.DEAD:
                # Duplicate creation (e.g. a node-death sweep re-driving
                # a spec that also took the normal path): creating a
                # second instance would strand queued calls in a mailbox
                # whose creation can never get resources. Release the
                # admission charge taken above — a swallowed duplicate
                # must not hold a phantom queued slot forever.
                self.quota_ledger.note_dequeued(spec)
                return
            # Register the mailbox immediately so method calls submitted
            # before the creation task is dispatched are queued, mirroring
            # the reference's client-side queueing while an actor is
            # PENDING_CREATION (direct_actor_task_submitter.h).
            self._actors[spec.actor_id] = _Actor(self, spec)
        elif type(spec) is QueuedTaskHeader and _perf_stats.ENABLED:
            _HEADERS_QUEUED.inc()
            _HEADER_BYTES.inc(spec.approx_nbytes())
        deps = spec.dependencies()
        unresolved = [d for d in deps if not self.worker.memory_store.contains(d)]
        if unresolved:
            # Charge the dep-parked demand BEFORE parking: the claim
            # (which releases it) can only happen after park, so the
            # counter never goes negative.
            self._dep_demand.add(self._spec_milli(spec))
            # Park before registering callbacks: a dep landing between
            # the contains() probe and on_ready registration fires the
            # callback inline, and dep_ready must find the entry.
            self._deps.park(spec.task_id.binary(), spec, unresolved)
            for d in unresolved:
                self.worker.memory_store.on_ready(d, self._on_dep_ready)
        else:
            if self._try_fast_dispatch(spec):
                return
            self._pending_add(spec)
            self._ready.put(spec)

    def _try_fast_dispatch(self, spec: TaskSpec) -> bool:
        """Submit-side dispatch bypass: a dependency-free normal task
        with the default strategy, no queue ahead of it, resources free,
        AND a warm idle executor goes straight to the executor pool —
        one thread handoff instead of three (submitter ->
        raylet-dispatch -> executor). This is the in-process analog of
        the reference's pipelined direct task submission. The idle-
        executor gate matters: without it a deep fan-out pays executor
        THREAD CREATION on the submit thread (measured 4x submit-rate
        loss at 30k-task bursts); the dispatcher loop remains the slow
        path for those, for parked work, placement groups, actor
        creations and infeasible requests."""
        if spec.kind != TaskKind.NORMAL_TASK:
            return False
        if type(spec.scheduling_strategy) is not DefaultSchedulingStrategy:
            return False
        # Racy reads are safe: a stale pending/idle value only routes
        # this task to the (always-correct) dispatcher path, or lets a
        # concurrently-submitted task (unordered anyway) jump the
        # queue; a task queued EARLIER by this thread always bumped
        # the pending count synchronously.
        if self._pending.count_approx != 0 or self._exec_idle == 0:
            return False
        if self._cancelled and spec.task_id.binary() in self._cancelled:
            return False
        try:
            request = self._spec_milli(spec)
        except Exception:
            return False  # malformed request: let the dispatcher report it
        if not self.resources.try_acquire(request):
            return False
        if not self.quota_ledger.try_acquire_cpu(spec):
            # Job at its CPU quota: the dispatcher path parks it behind
            # the job's own limit instead of the fast path running it.
            self.resources.release(request)
            return False
        self._launch(spec, self.resources, request)
        return True

    def _on_dep_ready(self, object_id: ObjectID) -> None:
        for spec in self._deps.dep_ready(object_id):
            self._dep_demand.remove(self._spec_milli(spec))
            self._pending_add(spec)
            self._ready.put(spec)

    def _submit_actor_task(self, spec: TaskSpec) -> None:
        actor = self._actors.get(spec.actor_id)
        if actor is None:
            self.worker.store_task_outputs(
                spec, None,
                error=exc.ActorDiedError(
                    spec.actor_id.hex()[:8], "actor handle refers to unknown actor"
                ),
            )
            return
        # State check and enqueue are atomic w.r.t. stop(): otherwise a kill
        # between the check and the put leaves this caller hanging forever.
        with actor.mb_lock:
            enqueued = actor.state != ActorState.DEAD
            if enqueued:
                # Dependencies still gate execution; ordering is preserved by
                # the mailbox (the actor executor blocks on unresolved deps
                # at dequeue time).
                actor.mailbox.put(spec)
                # Multi-slot actors admit up to max_slots concurrent
                # drain passes; a surplus activation that finds the
                # mailbox already drained simply retires.
                needs_activation = actor.pool_mode and \
                    actor.state == ActorState.ALIVE and \
                    actor._active_count < actor.max_slots
            cause = actor.death_cause
        if enqueued:
            if needs_activation:
                # Idle pool-mode actor: schedule a drain pass. PENDING
                # actors drain when their creation dispatches, and an
                # active pass sees this item before deactivating —
                # puts and the deactivation check share mb_lock.
                self._activate_actor(actor)
            return
        self.worker.store_task_outputs(
            spec, None, error=exc.ActorDiedError(spec.actor_id.hex()[:8], cause)
        )

    # ------------------------------------------------------------------
    # Dispatch loop (normal tasks + actor creations)
    # ------------------------------------------------------------------

    def _resource_pool_for(self, spec: TaskSpec) -> ResourceSet:
        strat = spec.scheduling_strategy
        if isinstance(strat, PlacementGroupSchedulingStrategy) and strat.placement_group is not None:
            idx = strat.placement_group_bundle_index
            pg_id = strat.placement_group.id
            if idx >= 0:
                pool = self.bundle_resources.get((pg_id, idx))
                if pool is None:
                    raise exc.PlacementGroupSchedulingError(
                        f"bundle {idx} of placement group {pg_id} is not reserved on this node"
                    )
                return pool
            # index -1: any bundle; pick first that can fit
            request = to_milli(spec.resources)
            for (gid, _i), pool in sorted(self.bundle_resources.items()):
                if gid == pg_id and pool.can_fit_total(request):
                    return pool
            raise exc.PlacementGroupSchedulingError(
                f"no bundle of placement group {pg_id} fits {spec.resources}"
            )
        return self.resources

    def _dispatch_loop(self):
        while not self._shutdown.is_set():
            try:
                if self._waiting_for_resources:
                    # Parked tasks exist: never block on the intake
                    # queue — resource releases (wait_for_change below)
                    # are the wake signal, and sleeping 0.1s here gated
                    # deep-queue drain to slots/0.1s regardless of how
                    # fast tasks actually finish.
                    spec = self._ready.get_nowait()
                else:
                    spec = self._ready.get(timeout=0.1)
            except queue.Empty:
                spec = None
            with self._lock:
                candidates = self._waiting_for_resources
                self._waiting_for_resources = []
            if spec is not None:
                candidates.append(spec)
                # Group-committed dispatch: drain whatever else is
                # already runnable into THIS pass (bounded), so a
                # burst of N queued creations/tasks costs O(N/batch)
                # loop iterations — not one full pass each. Order is
                # preserved (appended in queue order).
                try:
                    for _ in range(255):
                        candidates.append(self._ready.get_nowait())
                except queue.Empty:
                    pass
            still_waiting = []
            for s in candidates:
                if s.task_id.binary() in self._cancelled:
                    self._pending_remove(s)
                    self.quota_ledger.release_cpu(s)
                    self.worker.store_task_outputs(
                        s, None, error=exc.TaskCancelledError(s.describe())
                    )
                    continue
                try:
                    pool = self._resource_pool_for(s)
                    request = self._spec_milli(s)
                except Exception as e:  # malformed spec must not kill dispatch
                    self._pending_remove(s)
                    self.worker.store_task_outputs(
                        s, None,
                        error=e if isinstance(e, exc.RayTpuError)
                        else exc.RayTpuError(f"failed to schedule {s.describe()}: {e}"),
                    )
                    continue
                if not pool.can_fit_total(request):
                    self._pending_remove(s)
                    self.quota_ledger.release_cpu(s)
                    self.worker.store_task_outputs(
                        s, None, error=exc.RayTpuError(
                            f"task {s.describe()} requests {s.resources} which can "
                            f"never be satisfied by this node (total: {pool.total})"
                        )
                    )
                    continue
                if pool.try_acquire(request):
                    # Quota gate AFTER the pool acquire (same order as
                    # _try_fast_dispatch, pool rolled back on denial):
                    # the quota bounds concurrently RUNNING slots, so
                    # a spec that cannot run yet must not hold a
                    # charge that starves its job's smaller tasks.
                    # Actor CREATIONS are charged too (an actor holds
                    # its CPU slots for life — exempting them would
                    # let a tenant run its whole flood as actors);
                    # their charge releases on actor death, not task
                    # completion.
                    if s.kind in (TaskKind.NORMAL_TASK,
                                  TaskKind.ACTOR_CREATION) and \
                            not self.quota_ledger.try_acquire_cpu(s):
                        pool.release(request)
                        still_waiting.append(s)
                        continue
                    self._pending_remove(s)
                    self._launch(s, pool, request)
                else:
                    still_waiting.append(s)
            if still_waiting:
                with self._lock:
                    self._waiting_for_resources = still_waiting + self._waiting_for_resources
                if spec is None:
                    # nothing new arrived; wait for a release instead of spinning
                    self.resources.wait_for_change(timeout=0.05)

    def _launch(self, spec: TaskSpec, pool: ResourceSet, request: Dict[str, int]):
        self.quota_ledger.note_dequeued(spec)  # left the queue: dispatching
        if spec.kind == TaskKind.ACTOR_CREATION:
            actor = self._actors[spec.actor_id]
            if actor.state == ActorState.DEAD:  # killed while pending
                pool.release(request)
                self.quota_ledger.release_cpu(spec)
                return
            actor._held_pool = pool
            actor._held_request = request
            actor.start()
            return
        if type(spec) is QueuedTaskHeader:
            # Compact-queue dispatch boundary: the full TaskSpec exists
            # from here on (and only from here on). Latency is SAMPLED
            # 1/32 — two clock reads per dispatch would tax the path
            # the distribution exists to watch.
            tick = self._mat_tick = self._mat_tick + 1
            if tick & 31:
                spec = spec.materialize()
            else:
                t0 = _monotonic()
                spec = spec.materialize()
                _MATERIALIZE.record(_monotonic() - t0)
        # Reusable executor pool (reference: the worker pool keeps
        # warm workers; here threads): a thread PER task made thread
        # creation the single biggest per-task cost at fan-out
        # rates. Grows on demand (a task blocking in get() holds its
        # thread, idle==0 spawns another), shrinks on idle timeout.
        self._exec_submit(("task", spec, pool, request))

    def _exec_submit(self, item, spawn: bool = True) -> bool:
        """Enqueue one executor work item — a ("task", spec, pool,
        request) dispatch or an ("actor", actor) drain pass — growing
        the pool when no idle executor is promised to serve it.
        ``spawn=False`` is for re-activations from INSIDE an executor
        (that thread returns to the loop and serves the item itself —
        spawning would leak a thread per drain slice).

        Returns True when the item is accounted (an idle promise was
        consumed or a thread spawned). A ``spawn=False`` enqueue at
        idle==0 returns False: the item rides the CALLER's return to
        the loop, so the caller must skip its post-serve idle credit
        or the item double-counts as a phantom idle thread."""
        with self._exec_lock:
            self._exec_q.put(item)  # raylint: disable=R2 -- _exec_q is unbounded, so put() cannot block; enqueue + idle-count bookkeeping must be one atomic step or _exec_loop's retire check double-counts idle threads
            if self._exec_idle == 0:
                if not spawn:
                    return False
                t = threading.Thread(target=self._exec_loop,
                                     name="task-exec", daemon=True)
                self._exec_threads = [
                    th for th in self._exec_threads if th.is_alive()]
                self._exec_threads.append(t)
                t.start()
            else:
                self._exec_idle -= 1
            return True

    def _exec_loop(self):
        while not self._shutdown.is_set():
            try:
                item = self._exec_q.get(timeout=10.0)
            except queue.Empty:
                with self._exec_lock:
                    if not self._exec_q.empty():
                        continue  # a promised item landed: serve it
                    if self._exec_idle > 0:
                        self._exec_idle -= 1  # surplus: retire
                        return
                continue
            if item is None:
                return  # shutdown sentinel: retire immediately
            if item[0] == "actor":
                rode_this_thread = self._drain_actor(item[1])
            else:
                self._execute_normal_task(item[1], item[2], item[3])
                rode_this_thread = False
            with self._exec_lock:
                if not rode_this_thread:
                    self._exec_idle += 1

    # -- shared-executor actor serving (pool mode) ---------------------

    def _activate_actor(self, actor: "_Actor") -> None:
        """Schedule one drain pass for a pool-mode actor, bounded by
        its slot count (``max_slots`` = ``max_concurrency``): at
        max_concurrency=1 a single active pass preserves strict
        mailbox order; multi-slot actors serve up to max_slots items
        concurrently — the slot accounting, not thread count, is the
        concurrency bound."""
        with actor.mb_lock:
            if actor._active_count >= actor.max_slots:
                return
            actor._active_count += 1
        self._exec_submit(("actor", actor))

    # Mailbox items served per drain slice before the pass re-enqueues
    # itself, so one chatty actor cannot monopolize an executor while
    # other work queues.
    _ACTOR_DRAIN_SLICE = 64

    def _drain_actor(self, actor: "_Actor") -> bool:
        """One activation: construct if pending, then serve the mailbox
        until empty (deactivating under mb_lock, atomic with puts) or
        the fairness slice expires (re-enqueue, still active).

        Returns True when the slice re-enqueued itself UNACCOUNTED
        (``_exec_submit(spawn=False)`` at idle==0): the continuation
        rides this thread's return to the loop, so _exec_loop must not
        also credit the thread as idle."""
        if actor.state == ActorState.PENDING:
            # Only the creation-dispatch activation ever sees PENDING
            # (submits gate activation on ALIVE), but multi-slot makes
            # the invariant worth enforcing rather than assuming: a
            # PER-ACTOR ctor guard + re-check — the dedicated path's
            # global ctor lock would serialize a 10k-actor creation
            # storm across the whole pool.
            constructed = True
            with actor.ctor_lock:
                if actor.state == ActorState.PENDING:
                    constructed = actor._construct()
            if not constructed:
                # Constructor failed: _on_actor_death already drained
                # and poisoned the queued calls; retire the activation.
                with actor.mb_lock:
                    actor._active_count -= 1
                return False
        if actor.max_slots > 1:
            # Multi-slot fan-out: items that queued while this actor
            # was PENDING (or while every slot was busy) never
            # triggered an activation — bring concurrent passes up to
            # min(backlog, max_slots) so a burst actually uses the
            # slots. _activate_actor enforces the bound.
            backlog = actor.mailbox.qsize() - 1  # this pass serves one
            while backlog > 0:
                with actor.mb_lock:
                    if actor._active_count >= actor.max_slots:
                        break
                self._activate_actor(actor)
                backlog -= 1
        served = 0
        while True:
            try:
                item = actor.mailbox.get_nowait()
            except queue.Empty:
                with actor.mb_lock:
                    if actor.mailbox.empty():
                        actor._active_count -= 1
                        return False
                continue
            if item is None:
                continue  # stray dedicated-path sentinel: ignore
            if actor.state == ActorState.DEAD:
                self.worker.store_task_outputs(
                    item, None,
                    error=exc.ActorDiedError(actor.actor_id.hex()[:8],
                                             actor.death_cause))
                continue
            self._execute_actor_task(actor, item)
            served += 1
            if served >= self._ACTOR_DRAIN_SLICE and \
                    not self._shutdown.is_set():
                accounted = self._exec_submit(("actor", actor),
                                              spawn=False)
                # Still active: the re-enqueued pass continues. When
                # unaccounted, it continues ON THIS THREAD.
                return not accounted

    # ------------------------------------------------------------------
    # Execution
    # ------------------------------------------------------------------

    def _execute_normal_task(self, spec: TaskSpec, pool: ResourceSet,
                             request: Dict[str, int]):
        ctx = self.worker.task_context
        ctx.push(task_spec=spec, node_id=self.node_id, pool=pool, request=request)
        events = self.worker.task_events
        events.task_started(spec, self.node_id,
                            threading.current_thread().name)
        submitted = getattr(spec, "_submit_monotonic", None)
        if submitted is not None:
            _SCHED_LATENCY.record(_monotonic() - submitted)
            if _critical_path.enabled():
                _critical_path.record_stage(
                    _trace_id_of(spec), "sched.queue",
                    _monotonic() - submitted)
        try:
            from ray_tpu._private.runtime_env import applied_runtime_env

            args, kwargs = self.worker.resolve_args(spec)
            if spec.isolate_process:
                # Crash isolation: run in a worker process so an
                # os._exit / segfault fails this task, not the node.
                # "spawn" = one-shot fresh interpreter.
                result = self.worker_pool.run(
                    spec.func, args, kwargs, spec.runtime_env,
                    spawn=spec.isolate_process == "spawn", meta=spec)
            else:
                with applied_runtime_env(spec.runtime_env):
                    result = spec.func(*args, **kwargs)
            self.worker.store_task_outputs(spec, self._split_returns(spec, result))
            events.task_finished(spec)
        except BaseException as e:  # noqa: BLE001 - any user failure → object error
            events.task_finished(spec, error=f"{type(e).__name__}: {e}")
            self._handle_task_failure(spec, e)
        finally:
            ctx.pop()
            pool.release(request)
            # Tenancy CPU-slot release (token-guarded no-op for
            # unquota'd jobs): the job's parked work may dispatch now.
            self.quota_ledger.release_cpu(spec)

    def _execute_actor_task(self, actor: _Actor, spec: TaskSpec):
        ctx = self.worker.task_context
        ctx.push(task_spec=spec, node_id=self.node_id, pool=None, request=None)
        events = self.worker.task_events
        events.task_started(spec, self.node_id,
                            threading.current_thread().name)
        submitted = getattr(spec, "_submit_monotonic", None)
        if submitted is not None:
            # For actor tasks this is mailbox queue delay — the actor-
            # path backpressure signal.
            _SCHED_LATENCY.record(_monotonic() - submitted)
            if _critical_path.enabled():
                _critical_path.record_stage(
                    _trace_id_of(spec), "sched.queue",
                    _monotonic() - submitted)
        try:
            args, kwargs = self.worker.resolve_args(spec)
            if actor._proc is not None:
                result = actor._proc.request(("method", spec.func, args,
                                              kwargs))
            else:
                method = getattr(actor.instance, spec.func)
                if inspect.iscoroutinefunction(method):
                    result = actor._loop.run_until_complete(method(*args, **kwargs)) \
                        if actor._loop else asyncio.run(method(*args, **kwargs))
                else:
                    result = method(*args, **kwargs)
            self.worker.store_task_outputs(spec, self._split_returns(spec, result))
            events.task_finished(spec)
        except exc.WorkerCrashedError as e:
            # The actor's worker process died mid-call: restart the
            # actor (within max_restarts) — reference:
            # gcs_actor_manager.h restart FSM on worker failure. The
            # call itself replays on the replacement when its own
            # max_task_retries budget covers it (the restart-window
            # mailbox contract), else rejects naming the budget.
            events.task_finished(spec, error=f"WorkerCrashedError: {e}")
            self._handle_actor_crash(actor, str(e), inflight_spec=spec)
        except BaseException as e:  # noqa: BLE001
            events.task_finished(spec, error=f"{type(e).__name__}: {e}")
            err = e if isinstance(e, exc.TaskError) else exc.TaskError(e, spec.describe())
            self.worker.store_task_outputs(spec, None, error=err)
        finally:
            ctx.pop()

    def _split_returns(self, spec: TaskSpec, result: Any) -> list:
        if spec.num_returns == "dynamic":
            # Generator task (reference num_returns="dynamic"): each
            # yielded value becomes its own object at return indices
            # 1..k (index 0 is the generator ref itself); the task's
            # single return value is an ObjectRefGenerator over them.
            # Yielded objects are recorded on the spec so the cluster
            # report hook advertises their locations too.
            from ray_tpu._private.ids import ObjectID
            from ray_tpu.object_ref import ObjectRef, ObjectRefGenerator

            if not hasattr(result, "__iter__"):
                raise ValueError(
                    f"task {spec.describe()} declared "
                    "num_returns='dynamic' but returned non-iterable "
                    f"{type(result).__name__}")
            refs = []
            dynamic_ids = []
            try:
                for i, value in enumerate(result):
                    oid = ObjectID.for_task_return(spec.task_id, i + 1)
                    self.worker.memory_store.put(oid, value,
                                                 job_id=spec.job_id or "")
                    if self.worker.shm_plane is not None:
                        from ray_tpu._private.shm_plane import (
                            share_value,
                        )

                        share_value(self.worker, oid, value)
                    dynamic_ids.append(oid)
                    refs.append(ObjectRef(oid))
            except BaseException:
                # Mid-iteration failure: drop the partial puts — no ref
                # will ever exist for them, so leaving them would leak
                # store/shm memory proportional to what was yielded.
                refs.clear()  # handles unregister before eviction
                self.worker.memory_store.evict(dynamic_ids)
                plane = self.worker.shm_plane
                if plane is not None:
                    for oid in dynamic_ids:
                        try:
                            plane.release(oid)
                        except Exception:
                            pass
                raise
            spec.dynamic_return_ids = dynamic_ids
            return [ObjectRefGenerator(refs)]
        if spec.num_returns == 1:
            return [result]
        if spec.num_returns == 0:
            return []
        if not isinstance(result, (tuple, list)) or len(result) != spec.num_returns:
            raise ValueError(
                f"task {spec.describe()} declared num_returns={spec.num_returns} "
                f"but returned {type(result).__name__}"
            )
        return list(result)

    def _handle_task_failure(self, spec: TaskSpec, e: BaseException):
        retryable = False
        if spec.retry_exceptions is True:
            retryable = True
        elif isinstance(spec.retry_exceptions, (list, tuple)):
            retryable = isinstance(e, tuple(spec.retry_exceptions))
        if retryable and spec.max_retries != 0:
            spec.max_retries -= 1
            logger.warning(
                "task %s failed with %s, retrying (%s retries left)",
                spec.describe(), type(e).__name__, spec.max_retries,
            )
            self.submit(spec)
            return
        # Errors arriving from a dependency are already TaskErrors; propagate
        # them unchanged so the original cause surfaces at every get() site.
        err = e if isinstance(e, exc.TaskError) else exc.TaskError(e, spec.describe())
        self.worker.store_task_outputs(spec, None, error=err)

    def _handle_actor_crash(self, actor: _Actor, cause: str,
                            inflight_spec: Optional[TaskSpec] = None):
        """Worker-process death: restart in place if budget remains —
        queued calls survive onto the replacement, and the call that
        was EXECUTING replays ahead of them iff its own
        max_task_retries budget covers it (caller-visible
        replay-or-reject; the reject names the remaining budgets) —
        else die."""
        spec = actor.spec
        # Budget = in-place worker restarts here PLUS head-driven
        # node-death restarts recorded on the spec (restarts_used): the
        # two consume ONE max_restarts allowance, not one each.
        used = actor.num_restarts + getattr(spec, "restarts_used", 0)
        can_restart = spec.max_restarts == -1 or \
            used < spec.max_restarts
        drained = actor.stop(f"worker process crashed: {cause}")
        if actor._proc is not None:
            self.worker_pool.release_dedicated(actor._proc)
            actor._proc = None
        if can_restart:
            pool = getattr(actor, "_held_pool", None)
            if pool is not None:
                actor._held_pool = None
                pool.release(actor._held_request)
            replacement = _Actor(self, spec)
            replacement.num_restarts = actor.num_restarts + 1
            self._actors[actor.actor_id] = replacement
            if inflight_spec is not None:
                if inflight_spec.max_retries != 0:
                    # Replay FIRST — it was dispatched before everything
                    # still queued — charging its per-call budget.
                    if inflight_spec.max_retries > 0:
                        inflight_spec.max_retries -= 1
                    inflight_spec.attempt = getattr(
                        inflight_spec, "attempt", 0) + 1
                    replacement.mailbox.put(inflight_spec)
                else:
                    restarts_left = "-1 (infinite)" \
                        if spec.max_restarts == -1 else str(
                            spec.max_restarts - actor.num_restarts - 1)
                    self.worker.store_task_outputs(
                        inflight_spec, None,
                        error=exc.ActorUnavailableError(
                            f"call {inflight_spec.describe()} was "
                            f"executing when the actor's worker "
                            f"crashed and has no retries left "
                            f"(max_task_retries budget exhausted); "
                            f"actor is RESTARTING "
                            f"({restarts_left} restarts left)"))
            for item in drained:
                replacement.mailbox.put(item)
            self._pending_add(spec)
            self._ready.put(spec)
            return
        if inflight_spec is not None:
            self.worker.store_task_outputs(
                inflight_spec, None,
                error=exc.ActorDiedError(
                    actor.actor_id.hex()[:8],
                    f"{actor.death_cause}; restart budget exhausted "
                    f"(max_restarts={spec.max_restarts})"))
        for item in drained:
            self.worker.store_task_outputs(
                item, None,
                error=exc.ActorDiedError(actor.actor_id.hex()[:8],
                                         actor.death_cause))
        self._on_actor_death(actor, exc.ActorDiedError(
            actor.actor_id.hex()[:8], actor.death_cause))

    def _on_actor_death(self, actor: _Actor, error: BaseException):
        _fire_actor_death_hooks(actor.actor_id)
        if actor._proc is not None:
            self.worker_pool.release_dedicated(actor._proc)
            actor._proc = None
        # Idempotent: release lifetime resources exactly once — the
        # tenancy CPU charge is lifetime-held like the pool slots
        # (restarts keep it; only true death frees it).
        self.quota_ledger.release_cpu(actor.spec)
        pool = getattr(actor, "_held_pool", None)
        if pool is not None:
            actor._held_pool = None
            pool.release(actor._held_request)
        # Free the actor's name for reuse (a dead actor must not poison it).
        self.worker.gcs.remove_named_actor_by_id(actor.actor_id)
        # Fail everything that was still queued at death.
        drained = actor.stop(actor.death_cause or "actor died")
        # Death sweep over the dep-park table: a creation spec of THIS
        # actor still parked on unresolved deps is claimed here — or by
        # a racing _on_dep_ready, never both (DepTable's exactly-once
        # handoff; the loser's path is a no-op). Un-swept it would hold
        # its queued-ceiling admission forever if its dep never fires.
        aid = actor.actor_id
        for item in self._deps.sweep(
                lambda s: getattr(s, "actor_id", None) == aid):
            self._dep_demand.remove(self._spec_milli(item))
            self.quota_ledger.note_dequeued(item)
            drained.append(item)
        for item in drained:
            self.worker.store_task_outputs(
                item, None,
                error=exc.ActorDiedError(actor.actor_id.hex()[:8], actor.death_cause),
            )

    # ------------------------------------------------------------------
    # Control operations
    # ------------------------------------------------------------------

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        actor = self._actors.get(actor_id)
        if actor is None:
            return
        spec = actor.spec
        can_restart = not no_restart and (
            spec.max_restarts == -1
            or actor.num_restarts < spec.max_restarts)
        drained = actor.stop("killed via kill()")
        if actor._proc is not None:
            self.worker_pool.release_dedicated(actor._proc)
            actor._proc = None
        if can_restart:
            # Reference semantics (`gcs_actor_manager.h` restart FSM):
            # re-run the constructor; queued calls survive the restart.
            restarts = actor.num_restarts + 1
            pool = getattr(actor, "_held_pool", None)
            if pool is not None:
                actor._held_pool = None
                pool.release(actor._held_request)
            replacement = _Actor(self, spec)
            replacement.num_restarts = restarts
            self._actors[actor_id] = replacement
            for item in drained:
                replacement.mailbox.put(item)
            self._pending_add(spec)
            self._ready.put(spec)
            return
        for item in drained:
            self.worker.store_task_outputs(
                item, None,
                error=exc.ActorDiedError(actor_id.hex()[:8], actor.death_cause),
            )
        self._on_actor_death(actor, exc.ActorDiedError(actor_id.hex()[:8], "killed"))

    # Template-cached milli-demand (shared core with the head's
    # placement/reservation accounting — resources.spec_milli).
    _spec_milli = staticmethod(spec_milli)

    def _pending_add(self, spec) -> None:
        self._pending.add(self._spec_milli(spec))

    def _pending_remove(self, spec) -> None:
        self.quota_ledger.note_dequeued(spec)
        self._pending.remove(self._spec_milli(spec))

    def pending_demand_milli(self) -> Dict[str, int]:
        """Resource demand of tasks queued but not yet dispatched — the
        backlog signal the cluster scheduler and autoscaler consume
        (reference: raylet backlog reporting in lease requests).
        Maintained incrementally: O(1) per read. Header-queued and
        spec-queued work charge identically (both flow _pending_add
        with the template-cached milli conversion)."""
        return self._pending.demand_milli()

    def backlog_count(self) -> int:
        return self._pending.count()

    def dep_parked_demand_milli(self) -> Dict[str, int]:
        """Demand of dependency-parked work — not runnable yet, so not
        in the backlog signal, but placement of lifetime-pinned work
        (actor creations) must reserve for it."""
        return self._dep_demand.demand_milli()

    def queue_depths(self) -> Dict[str, int]:
        """Scheduler-pressure snapshot for the health plane: tasks
        queued but not dispatched (``backlog``), the subset parked
        waiting for resources, and tasks parked on unresolved
        dependencies (headers and full specs count identically).
        O(1) except the parked list length."""
        with self._lock:
            parked = len(self._waiting_for_resources)
        return {
            "backlog": self._pending.count(),
            "parked_for_resources": parked,
            "waiting_for_deps": self._deps.waiting_count(),
        }

    def actor_state(self, actor_id: ActorID) -> str:
        actor = self._actors.get(actor_id)
        return actor.state if actor else ActorState.DEAD

    def cancel(self, task_id) -> None:
        self._cancelled.add(task_id.binary())

    # -- blocked-worker resource release (block/unblock protocol) --------

    def notify_blocked(self):
        """Called when a worker thread blocks in get(): temporarily release
        its CPU share so other tasks can run (avoids nested-get deadlock)."""
        ctx = self.worker.task_context.current()
        if ctx is None or ctx.get("pool") is None:
            return
        request = ctx.get("request") or {}
        cpu_part = {k: v for k, v in request.items() if k == "CPU" and v > 0}
        if cpu_part:
            ctx["pool"].release(cpu_part)
            self._blocked.stack.append((ctx["pool"], cpu_part))

    def notify_unblocked(self):
        if not getattr(self._blocked, "stack", None):
            return
        pool, cpu_part = self._blocked.stack.pop()
        # Reacquire before continuing; spin on the condition variable.
        while not pool.try_acquire(cpu_part):
            pool.wait_for_change(timeout=0.05)

    def shutdown(self):
        self._shutdown.set()
        # Head role with a sharded control plane: drain the write-behind
        # replication stream first, so a GRACEFUL exit establishes the
        # acked-durable boundary (crash exits intentionally skip this —
        # their loss bound is each shard's open group-commit window).
        head = getattr(self, "head", None)
        router = getattr(head, "shard_router", None) \
            if head is not None else None
        if router is not None:
            try:
                router.flush()
            except Exception:
                pass
        for actor in list(self._actors.values()):
            actor.stop("node shutdown")
            if actor._proc is not None:
                if self._worker_pool is not None:
                    self._worker_pool.release_dedicated(actor._proc)
                else:
                    actor._proc.kill()
                actor._proc = None
        if getattr(self, "_memory_monitor", None) is not None:
            self._memory_monitor.stop()
        if self._worker_pool is not None:
            self._worker_pool.shutdown()
        # Wake every executor blocked in its 10s mailbox poll with a
        # sentinel, then join what can be joined (bounded; never joins
        # the calling thread — shutdown can arrive from a task). A
        # daemon thread would die with the process anyway, but a
        # LONG-LIVED process (a test suite, a driver serving many jobs)
        # must get its threads back at shutdown, not at exit — the leak
        # sanitizer enforces exactly this.
        with self._exec_lock:
            exec_threads = [t for t in self._exec_threads
                            if t.is_alive()]
            for _ in exec_threads:
                self._exec_q.put(None)  # raylint: disable=R2 -- _exec_q is unbounded so put() cannot block; the sentinel count must match the thread census taken under this same hold
        self._dispatcher.join(timeout=1.0)
        me = threading.current_thread()
        joinable = exec_threads + [
            t for actor in list(self._actors.values())
            for t in actor._threads]
        deadline = _monotonic() + 2.0  # shared budget, not per-thread
        for t in joinable:
            if t is not me:
                t.join(timeout=max(0.0, deadline - _monotonic()))
