"""Persistent XLA compilation cache.

Role-equivalent to the reference's lack of one — serving cold starts
there are hidden by long-lived GPU replicas; on TPU the first request
hitting an uncompiled program costs the full XLA compile (measured 14 s
TTFT for the LLM engine in round 3). Enabling JAX's on-disk compilation
cache makes every process after the first load compiled executables
instead of recompiling, and `LLMEngine.warmup()` moves the remaining
first-process compile to deploy time.
"""

from __future__ import annotations

import os

_enabled = False

DEFAULT_DIR = os.environ.get(
    "RAY_TPU_COMPILE_CACHE",
    os.path.join(os.path.expanduser("~"), ".cache", "ray_tpu_xla"))


def enable_persistent_cache(path: str | None = None) -> bool:
    """Idempotently point JAX at an on-disk compilation cache. Returns
    True if the cache is active. Set RAY_TPU_COMPILE_CACHE="" to opt
    out."""
    global _enabled
    if _enabled:
        return True
    target = DEFAULT_DIR if path is None else path
    if not target:
        return False  # explicitly disabled
    try:
        import jax

        if jax.default_backend() == "cpu" and \
                not os.environ.get("RAY_TPU_COMPILE_CACHE"):
            # CPU AOT results are machine-feature-sensitive (XLA warns
            # mismatched loads "could lead to SIGILL"); the cache's win
            # is on accelerators, so CPU only opts in explicitly.
            return False
        os.makedirs(target, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", target)
        # Cache even quick compiles: the serving path compiles many
        # small-bucket programs whose combined cost is what hurts.
        try:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.2)
        except Exception:
            pass  # older knob name; the dir alone still works
        _enabled = True
        return True
    except Exception:
        return False
