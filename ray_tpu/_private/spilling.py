"""Object spilling: disk (or pluggable external) backing for the store.

Role-equivalent to the reference's spill pipeline — the raylet's
LocalObjectManager picks objects to spill under memory pressure
(`src/ray/raylet/local_object_manager.h:41` SpillObjects), IO workers run
the actual writes through an ExternalStorage implementation
(`python/ray/_private/external_storage.py:72`, FileSystemStorage `:246`),
and spilled objects restore transparently on get.

Here the memory store calls `SpillManager.maybe_spill()` after each put;
the manager serializes cold, large, ready objects out to the storage
backend and drops the in-memory value, leaving the URL on the entry.
`get`/`peek` restore through `SpillManager.restore()`. Ref release
deletes the spilled file.

Budget and thresholds come from the config table
(`object_store_memory_bytes`, `object_spilling_threshold`,
`min_spilling_size_bytes` — reference: ray_config_def.h spilling flags).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

import cloudpickle

from ray_tpu._private import critical_path
from ray_tpu._private import perf_stats as _perf_stats
from ray_tpu._private import sanitize_hooks
from ray_tpu._private.config import ray_config
from ray_tpu._private.ids import ObjectID

# Object-plane observability: spill/restore volume, exported as
# ray_tpu_object_*_total by the runtime-metrics fold.
_SPILL_BYTES = _perf_stats.counter("object_spill_bytes")
_RESTORE_BYTES = _perf_stats.counter("object_restore_bytes")


def decode_spilled_payload(raw: bytes):
    """Decode one spilled payload: RTS1-framed arena bytes (sealed
    layout, buffers viewing the loaded copy) or plain cloudpickle —
    the ONE sniff both transparent restore and lineage
    restore-from-spill share."""
    if raw[:4] == b"RTS1":
        from ray_tpu._private.shm_plane import decode_payload

        return decode_payload(raw)
    return cloudpickle.loads(raw)


def restore_spilled_payload(url: str):
    """Restore a spilled object from its URL without a SpillManager —
    the lineage-reconstruction path: a dead node's spill file outlives
    the process, and the head restores the value from disk instead of
    re-executing the creating task."""
    assert url.startswith("file://"), url
    with open(url[len("file://"):], "rb") as f:
        raw = f.read()
    _RESTORE_BYTES.inc(len(raw))
    return decode_spilled_payload(raw)


def estimate_size(value) -> int:
    """Cheap recursive size estimate — exact for buffers/arrays (where
    the bytes are), rough for object graphs (which spilling doesn't
    target anyway)."""
    try:
        import numpy as np

        if isinstance(value, np.ndarray):
            return int(value.nbytes)
    except ImportError:  # pragma: no cover
        pass
    nbytes = getattr(value, "nbytes", None)
    if isinstance(nbytes, int):  # jax arrays, arrow buffers
        return nbytes
    if isinstance(value, (bytes, bytearray, memoryview)):
        return len(value)
    if isinstance(value, str):
        return len(value)
    if isinstance(value, (list, tuple, set)):
        return 64 + sum(estimate_size(v) for v in list(value)[:100])
    if isinstance(value, dict):
        return 64 + sum(estimate_size(k) + estimate_size(v)
                        for k, v in list(value.items())[:100])
    return 256


class ExternalStorage:
    """Reference: `python/ray/_private/external_storage.py:72`."""

    def spill(self, object_id: ObjectID, payload: bytes) -> str:
        raise NotImplementedError

    def restore(self, url: str) -> bytes:
        raise NotImplementedError

    def delete(self, urls: List[str]) -> None:
        raise NotImplementedError

    def destroy(self) -> None:
        pass


class FileSystemStorage(ExternalStorage):
    """Reference: FileSystemStorage (`external_storage.py:246`)."""

    def __init__(self, directory: Optional[str] = None):
        import tempfile

        self.directory = directory or os.path.join(
            tempfile.gettempdir(), f"ray_tpu_spill_{os.getpid()}")
        # Directory creation is deferred to the first spill: most
        # processes never exceed the budget and never touch disk.

    def spill(self, object_id: ObjectID, payload: bytes) -> str:
        os.makedirs(self.directory, exist_ok=True)
        # Unique per WRITE, not per object: the heap sweep and the
        # arena spill can both write a copy of the same oid (a swap
        # racing a sweep snapshot); with a deterministic path the
        # loser's cleanup would unlink the winner's live file.
        path = os.path.join(
            self.directory,
            f"{object_id.hex()}-{os.urandom(4).hex()}")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)  # atomic: never observe partial spills
        return f"file://{path}"

    def restore(self, url: str) -> bytes:
        assert url.startswith("file://"), url
        with open(url[len("file://"):], "rb") as f:
            return f.read()

    def delete(self, urls: List[str]) -> None:
        for url in urls:
            try:
                os.unlink(url[len("file://"):])
            except OSError:
                pass

    def destroy(self) -> None:
        import shutil

        shutil.rmtree(self.directory, ignore_errors=True)


class SpillManager:
    """Memory accounting + spill/restore orchestration for a MemoryStore.

    The store reports puts/accesses; when in-memory bytes exceed
    threshold * budget, cold large objects spill until back under."""

    def __init__(self, store, storage: Optional[ExternalStorage] = None,
                 budget_bytes: Optional[int] = None):
        self.store = store
        self.storage = storage or FileSystemStorage()
        self.budget = budget_bytes or ray_config.object_store_memory_bytes
        self._lock = threading.Lock()
        # Serializes spill sweeps: two concurrent maybe_spill calls on
        # the same object would double-write its (deterministic) path
        # and the loser's cleanup would unlink the winner's live file.
        self._spill_mutex = threading.Lock()
        self.in_memory_bytes = 0
        self.spilled_bytes = 0
        self.num_spilled = 0
        self.num_restored = 0

    # -- accounting hooks (store calls these under its own lock) ---------

    def note_put(self, size: int) -> None:
        with self._lock:
            self.in_memory_bytes += size

    def note_drop(self, size: int) -> None:
        with self._lock:
            self.in_memory_bytes -= size

    def over_threshold(self) -> bool:
        return self.in_memory_bytes > \
            self.budget * ray_config.object_spilling_threshold

    # -- spill/restore ----------------------------------------------------

    def maybe_spill(self) -> int:
        """Spill cold objects until under threshold. Returns bytes
        spilled. Called outside the store lock (serialization is slow)."""
        if not self.over_threshold():
            return 0
        if not self._spill_mutex.acquire(blocking=False):
            return 0  # another thread is already sweeping
        try:
            return self._spill_locked()
        finally:
            self._spill_mutex.release()

    def _spill_locked(self) -> int:
        target = int(self.budget * ray_config.object_spilling_threshold)
        spilled = 0
        for oid, value, size, existing_url in self.store.spill_candidates():
            with self._lock:
                if self.in_memory_bytes <= target:
                    break
            if existing_url is not None:
                # Restored copy: the bytes are already on disk — just
                # drop the resident value again.
                if self.store.mark_spilled(oid, existing_url):
                    spilled += size
                    with self._lock:
                        self.in_memory_bytes -= size
                continue
            t0 = time.monotonic()
            payload = cloudpickle.dumps(value)
            url = self.storage.spill(oid, payload)
            if critical_path.enabled():
                critical_path.record_stage(
                    critical_path.ambient_trace_id(), "object.spill",
                    time.monotonic() - t0)
            sanitize_hooks.crash_point("spill.write.after")
            sanitize_hooks.sched_point("spill.mark")
            if self.store.mark_spilled(oid, url):
                spilled += size
                _SPILL_BYTES.inc(len(payload))
                with self._lock:
                    self.in_memory_bytes -= size
                    self.spilled_bytes += len(payload)
                    self.num_spilled += 1
            else:  # entry vanished meanwhile: drop the file
                self.storage.delete([url])
        return spilled

    def spill_payload(self, object_id: ObjectID, payload: bytes) -> str:
        """Write an already-serialized payload (a shm arena object's
        RTS1 bytes — see ``shm_plane.payload_bytes``) to the storage
        backend. The caller flips its own entry; accounting here."""
        t0 = time.monotonic()
        url = self.storage.spill(object_id, payload)
        if critical_path.enabled():
            critical_path.record_stage(
                critical_path.ambient_trace_id(), "object.spill",
                time.monotonic() - t0)
        sanitize_hooks.crash_point("spill.write.after")
        _SPILL_BYTES.inc(len(payload))
        with self._lock:
            self.spilled_bytes += len(payload)
            self.num_spilled += 1
        return url

    def restore(self, url: str):
        t0 = time.monotonic()
        raw = self.storage.restore(url)
        _RESTORE_BYTES.inc(len(raw))
        if critical_path.enabled():
            critical_path.record_stage(
                critical_path.ambient_trace_id(), "object.restore",
                time.monotonic() - t0)
        sanitize_hooks.sched_point("spill.restore")
        value = decode_spilled_payload(raw)
        with self._lock:
            self.num_restored += 1
        return value

    def delete(self, urls: List[str]) -> None:
        self.storage.delete(urls)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "in_memory_bytes": self.in_memory_bytes,
                "spilled_bytes": self.spilled_bytes,
                "num_spilled": self.num_spilled,
                "num_restored": self.num_restored,
            }
