"""Built-in runtime metrics (the reference's canonical stats).

Reference: `src/ray/stats/metric_defs.cc` — STATS_tasks / STATS_actors /
scheduler / object-store gauges exported alongside user metrics. Here
the same canonical series are computed ON EXPORT from live runtime state
(task-event buffer, actor registry, memory store, resources), so there's
no bookkeeping on the hot path; `collect_runtime_metrics()` refreshes
the gauges and the Prometheus endpoint calls it before rendering.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

from ray_tpu.util.metrics import Gauge

_gauges: Dict[str, Gauge] = {}
_prev_tags: Dict[str, set] = {}

# Library-side stats sources (serve ingress, …). The core exporter must
# not import upward into library packages (raylint R3), so libraries
# register a provider here at import time instead: ``provider() ->
# Optional[Dict[key, number]]`` plus a key -> (gauge_name, description)
# series map. A provider returning None contributes nothing this scrape.
_EXT_PROVIDERS: Dict[str, Tuple[Callable, Dict[str, Tuple[str, str]]]] = {}


def register_stats_provider(name: str, provider: Callable,
                            series: Dict[str, Tuple[str, str]]) -> None:
    _EXT_PROVIDERS[name] = (provider, series)


def unregister_stats_provider(name: str) -> None:
    """Remove a library stats provider (a redeployed/stopped library
    must be able to retire its scrape hook; raylint R7)."""
    _EXT_PROVIDERS.pop(name, None)


def reset_interned() -> None:
    """Drop the interned gauge handles and fresh-snapshot tag memory
    (raylint R7's reset-capable API for this module's registries).
    Safe at any time: ``_gauge`` re-interns on the next scrape and the
    underlying ``util.metrics`` registry keys by name, so re-created
    handles alias the same exported series."""
    _gauges.clear()
    _prev_tags.clear()


def _collect_ext_providers() -> None:
    for provider, series in list(_EXT_PROVIDERS.values()):
        try:
            stats = provider()
        except Exception:
            continue
        if stats is None:
            continue
        for key, (gauge_name, desc) in series.items():
            _gauge(gauge_name, desc).set(float(stats.get(key, 0)))


def _gauge(name: str, desc: str, tag_keys=()) -> Gauge:
    g = _gauges.get(name)
    if g is None:
        g = _gauges[name] = Gauge(name, desc, tag_keys=tag_keys)
    return g


def _set_series(name: str, desc: str, tag_key: str,
                values: Dict[str, float]) -> None:
    """Set a tagged gauge from a fresh snapshot, zeroing series whose
    tag vanished (a state with no members must read 0, not its last
    nonzero value — and a fresh session must not export the previous
    cluster's counts)."""
    _set_multi_series(name, desc, (tag_key,),
                      {(tag,): v for tag, v in values.items()})


def _set_multi_series(name: str, desc: str, tag_keys: Tuple[str, ...],
                      values: Dict[Tuple[str, ...], float]) -> None:
    """_set_series for composite tag sets (e.g. (job, state)): same
    fresh-snapshot semantics with vanished tag combinations zeroed."""
    g = _gauge(name, desc, tag_keys=tag_keys)
    current = set(values)
    for stale in _prev_tags.get(name, set()) - current:
        g.set(0.0, tags=dict(zip(tag_keys, stale)))
    for tags, v in values.items():
        g.set(float(v), tags=dict(zip(tag_keys, tags)))
    _prev_tags[name] = current


# Dists whose p99 is a first-class dashboard series: the critical-path
# attribution vectors are p50/p99 by contract, and the serve dashboard
# already charts TTFT p99.
_P99_DISTS = frozenset({"request_stage_seconds", "serve_ttft_seconds"})


def _collect_fastpath_stats() -> None:
    """Fold the lock-free fast-path stats (`_private/perf_stats.py` —
    batcher queue delay/flush size, submit→start latency, intern hit
    rate, SQLite group-commit latency, wait wake-ups, serve route
    latencies) into the registry as gauges: distributions export
    ``_p50``/``_p95``/``_count``/``_sum`` series, counters export
    ``_total``. Computed only here, on scrape — the hot paths pay two
    integer adds per observation, nothing more."""
    from ray_tpu._private import perf_stats

    for name, tags, stat in perf_stats.stats_items():
        tag_keys = tuple(k for k, _ in tags)
        tag_dict = dict(tags) or None
        if isinstance(stat, perf_stats.Counter):
            _gauge(f"ray_tpu_{name}_total",
                   f"fast-path counter {name}",
                   tag_keys=tag_keys).set(float(stat.value),
                                          tags=tag_dict)
            continue
        base = f"ray_tpu_{name}"
        _gauge(f"{base}_p50", f"fast-path {name} p50",
               tag_keys=tag_keys).set(stat.quantile(0.5), tags=tag_dict)
        _gauge(f"{base}_p95", f"fast-path {name} p95",
               tag_keys=tag_keys).set(stat.quantile(0.95), tags=tag_dict)
        if name in _P99_DISTS:
            # Tail-attribution series (the dashboards chart p99 for
            # these); kept opt-in by name so every dist doesn't grow a
            # third quantile gauge.
            _gauge(f"{base}_p99", f"fast-path {name} p99",
                   tag_keys=tag_keys).set(stat.quantile(0.99),
                                          tags=tag_dict)
        _gauge(f"{base}_count", f"fast-path {name} observations",
               tag_keys=tag_keys).set(float(stat.total), tags=tag_dict)
        _gauge(f"{base}_sum", f"fast-path {name} sum",
               tag_keys=tag_keys).set(stat.sum, tags=tag_dict)


def _collect_node_stats() -> None:
    """Physical node stats (`node_stats.sample_node_stats` — the
    reporter-agent psutil sample) as ``ray_tpu_node_*`` gauges: every
    process exports its own node's sample, so worker-node snapshots
    ship them and the head's merged exposition carries one
    ``node="<id>"``-tagged series set per node."""
    from ray_tpu._private.node_stats import sample_node_stats

    stats = sample_node_stats()
    for key, gauge_name, desc in (
            ("cpu_percent", "ray_tpu_node_cpu_percent",
             "Node CPU utilization percent"),
            ("cpu_count", "ray_tpu_node_cpu_count", "Node CPU count"),
            ("mem_total", "ray_tpu_node_mem_total_bytes",
             "Node total memory bytes"),
            ("mem_available", "ray_tpu_node_mem_available_bytes",
             "Node available memory bytes"),
            ("mem_percent", "ray_tpu_node_mem_percent",
             "Node memory utilization percent"),
            ("disk_total", "ray_tpu_node_disk_total_bytes",
             "Node root-disk total bytes"),
            ("disk_free", "ray_tpu_node_disk_free_bytes",
             "Node root-disk free bytes"),
            ("disk_percent", "ray_tpu_node_disk_percent",
             "Node root-disk utilization percent"),
            ("pid_count", "ray_tpu_node_pid_count",
             "Node process count")):
        v = stats.get(key)
        if v is not None:
            _gauge(gauge_name, desc).set(float(v))
    la = stats.get("load_avg")
    if la:
        _gauge("ray_tpu_node_load_1m", "Node 1-minute load average").set(
            float(la[0]))


def _collect_job_metrics(w) -> None:
    """Per-job resource accounting as ``job="<id>"``-tagged series. On
    a cluster head the task-event side is CLUSTER-wide (the shipping
    plane's merged view); object accounting is per process — node
    snapshots ship their own, node-tagged in the merged exposition.

    The event fold is fingerprint-cached: the cluster merge is O(all
    stored events) and runs every scrape/ship cycle on the head, so an
    idle cluster must not pay a repeated 200k-event walk — the buffer
    and aggregator mutation seqs tell us when nothing moved."""
    from ray_tpu._private.obs_plane import cluster_task_events

    buf = getattr(w, "task_events", None)
    head = getattr(w, "cluster_head", None)
    agg = getattr(head, "obs", None) if head is not None else None
    fp = (buf.mutation_seq if buf is not None else -1,
          agg.mutation_seq if agg is not None else -1)
    cached = getattr(w, "_job_metrics_cache", None)
    if cached is not None and cached[0] == fp:
        tasks, cpu = cached[1], cached[2]
    else:
        tasks: Dict[Tuple[str, ...], float] = {}
        cpu: Dict[Tuple[str, ...], float] = {}
        for ev in cluster_task_events(w, sort=False):
            if not ev.job_id:
                continue
            key = (ev.job_id, ev.state)
            tasks[key] = tasks.get(key, 0) + 1
            dur = ev.duration_s()
            if dur:
                ckey = (ev.job_id,)
                cpu[ckey] = cpu.get(ckey, 0.0) + dur
        w._job_metrics_cache = (fp, tasks, cpu)
    _set_multi_series("ray_tpu_job_tasks", "Tasks by job and state",
                      ("job", "state"), tasks)
    _set_multi_series("ray_tpu_job_cpu_seconds",
                      "Cumulative task execution seconds by job",
                      ("job",), cpu)
    store = getattr(w, "memory_store", None)
    if store is not None and hasattr(store, "job_object_stats"):
        objs: Dict[Tuple[str, ...], float] = {}
        obj_bytes: Dict[Tuple[str, ...], float] = {}
        for job, (n, nbytes) in store.job_object_stats().items():
            if not job:
                continue  # untagged: no job="" metric series
            objs[(job,)] = float(n)
            obj_bytes[(job,)] = float(nbytes)
        _set_multi_series("ray_tpu_job_objects",
                          "Objects owned in the object store by job",
                          ("job",), objs)
        _set_multi_series("ray_tpu_job_object_store_bytes",
                          "Estimated object-store bytes owned by job",
                          ("job",), obj_bytes)
    # Tenancy enforcement state: live quota usage per job (the
    # rejection/park/rate-limit counters ride the fast-path fold as
    # ray_tpu_job_quota_*_total / ray_tpu_job_rate_limited_total).
    ledger = getattr(getattr(w, "backend", None), "quota_ledger", None)
    if ledger is not None:
        cpu_used: Dict[Tuple[str, ...], float] = {}
        queued: Dict[Tuple[str, ...], float] = {}
        parked: Dict[Tuple[str, ...], float] = {}
        for job in ledger.jobs():
            if not job:
                continue
            u = ledger.usage(job)
            cpu_used[(job,)] = float(u["cpu_milli"])
            queued[(job,)] = float(u["queued"])
            parked[(job,)] = float(u["parked"])
        _set_multi_series("ray_tpu_job_quota_cpu_milli",
                          "Running milli-CPU charged against the "
                          "job's quota", ("job",), cpu_used)
        _set_multi_series("ray_tpu_job_quota_queued",
                          "Tasks admitted against the job's "
                          "queued-task ceiling", ("job",), queued)
        _set_multi_series("ray_tpu_job_quota_parked",
                          "Tasks parked behind the job's CPU quota",
                          ("job",), parked)
    plane = getattr(w, "shm_plane", None)
    if plane is not None and hasattr(plane, "job_arena_bytes"):
        arena: Dict[Tuple[str, ...], float] = {}
        for job, nbytes in plane.job_arena_bytes().items():
            if job:
                arena[(job,)] = float(nbytes)
        _set_multi_series("ray_tpu_job_arena_bytes",
                          "Shared-arena bytes charged to the "
                          "producing job", ("job",), arena)


def collect_runtime_metrics() -> None:
    """Refresh the canonical runtime gauges from live state. Cheap
    (reads in-process tables); safe to call on every scrape."""
    from ray_tpu._private import worker as worker_mod

    try:
        _collect_fastpath_stats()
    except Exception:
        pass
    _collect_ext_providers()
    try:
        _collect_node_stats()
    except Exception:
        pass
    # Health/SLO plane: burn-rate + loop-lag + pressure + scheduler
    # queue-depth gauges (what per-node /api/healthz verdicts read out
    # of shipped snapshots).
    try:
        from ray_tpu._private.health import collect_health_metrics

        collect_health_metrics()
    except Exception:
        pass

    w = worker_mod.global_worker_or_none()
    if w is None:
        return

    # Tasks by state (reference STATS_tasks).
    by_state: Dict[str, float] = {}
    try:
        for ev in w.task_events.list_events():
            by_state[ev.state] = by_state.get(ev.state, 0) + 1
    except Exception:
        pass
    _set_series("ray_tpu_tasks", "Tasks by state", "state", by_state)

    # Per-job attribution series (job-tagged tasks/cpu/objects).
    try:
        _collect_job_metrics(w)
    except Exception:
        pass

    # Actors by state (reference STATS_actors).
    try:
        actors = getattr(w.backend, "_actors", {})
        a_by_state: Dict[str, float] = {}
        for actor in list(actors.values()):
            a_by_state[actor.state] = a_by_state.get(actor.state, 0) + 1
        _set_series("ray_tpu_actors", "Actors by state", "state",
                    a_by_state)
    except Exception:
        pass

    # Object store occupancy (reference object_store_memory stats).
    try:
        store = w.memory_store
        with store._lock:
            entries = list(store._entries.values())
        n_objects = len(entries)
        n_bytes = float(sum(e.size or 0 for e in entries))
        _gauge("ray_tpu_object_store_objects",
               "Objects resident in the in-process store").set(
            float(n_objects))
        _gauge("ray_tpu_object_store_bytes",
               "Estimated bytes resident in the in-process store").set(
            n_bytes)
        spilled = sum(1 for e in entries if e.spilled_url)
        _gauge("ray_tpu_object_store_spilled_objects",
               "Objects currently spilled to external storage").set(
            float(spilled))
    except Exception:
        pass

    # Resource slots (reference scheduler resource gauges).
    try:
        res = w.backend.resources
        _set_series("ray_tpu_resources_total", "Total node resources",
                    "resource", dict(res.total))
        _set_series("ray_tpu_resources_available",
                    "Available node resources", "resource",
                    dict(res.available))
    except Exception:
        pass

    # Shared-memory plane stats when installed (plasma stats role).
    try:
        plane = getattr(w, "shm_plane", None)
        if plane is not None:
            st = plane.store.stats()
            items = st.items() if isinstance(st, dict) else (
                (f, getattr(st, f)) for f in dir(st)
                if not f.startswith("_"))
            for field, val in items:
                if isinstance(val, (int, float)):
                    _gauge(f"ray_tpu_shm_{field}",
                           f"Shared-memory store {field}").set(
                        float(val))
    except Exception:
        pass
