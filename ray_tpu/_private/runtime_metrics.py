"""Built-in runtime metrics (the reference's canonical stats).

Reference: `src/ray/stats/metric_defs.cc` — STATS_tasks / STATS_actors /
scheduler / object-store gauges exported alongside user metrics. Here
the same canonical series are computed ON EXPORT from live runtime state
(task-event buffer, actor registry, memory store, resources), so there's
no bookkeeping on the hot path; `collect_runtime_metrics()` refreshes
the gauges and the Prometheus endpoint calls it before rendering.
"""

from __future__ import annotations

from typing import Dict, Optional

from ray_tpu.util.metrics import Gauge

_gauges: Dict[str, Gauge] = {}


def _gauge(name: str, desc: str, tag_keys=()) -> Gauge:
    g = _gauges.get(name)
    if g is None:
        g = _gauges[name] = Gauge(name, desc, tag_keys=tag_keys)
    return g


def collect_runtime_metrics() -> None:
    """Refresh the canonical runtime gauges from live state. Cheap
    (reads in-process tables); safe to call on every scrape."""
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker_or_none()
    if w is None:
        return

    # Tasks by state (reference STATS_tasks).
    by_state: Dict[str, int] = {}
    try:
        for ev in w.task_events.list_events():
            by_state[ev.state] = by_state.get(ev.state, 0) + 1
    except Exception:
        pass
    g = _gauge("ray_tpu_tasks", "Tasks by state", tag_keys=("state",))
    for state, n in by_state.items():
        g.set(float(n), tags={"state": state})

    # Actors by state (reference STATS_actors).
    try:
        actors = getattr(w.backend, "_actors", {})
        a_by_state: Dict[str, int] = {}
        for actor in list(actors.values()):
            a_by_state[actor.state] = a_by_state.get(actor.state, 0) + 1
        g = _gauge("ray_tpu_actors", "Actors by state",
                   tag_keys=("state",))
        for state, n in a_by_state.items():
            g.set(float(n), tags={"state": state})
    except Exception:
        pass

    # Object store occupancy (reference object_store_memory stats).
    try:
        store = w.memory_store
        with store._lock:
            entries = list(store._entries.values())
        n_objects = len(entries)
        n_bytes = float(sum(e.size or 0 for e in entries))
        _gauge("ray_tpu_object_store_objects",
               "Objects resident in the in-process store").set(
            float(n_objects))
        _gauge("ray_tpu_object_store_bytes",
               "Estimated bytes resident in the in-process store").set(
            n_bytes)
        spilled = sum(1 for e in entries if e.spilled_url)
        _gauge("ray_tpu_object_store_spilled_objects",
               "Objects currently spilled to external storage").set(
            float(spilled))
    except Exception:
        pass

    # Resource slots (reference scheduler resource gauges).
    try:
        res = w.backend.resources
        from ray_tpu._private.resources import from_milli

        total = from_milli(getattr(res, "total_milli", None) or {}) \
            if hasattr(res, "total_milli") else dict(res.total)
        avail = dict(res.available)
        gt = _gauge("ray_tpu_resources_total", "Total node resources",
                    tag_keys=("resource",))
        ga = _gauge("ray_tpu_resources_available",
                    "Available node resources", tag_keys=("resource",))
        for k, v in total.items():
            gt.set(float(v), tags={"resource": k})
        for k, v in avail.items():
            ga.set(float(v), tags={"resource": k})
    except Exception:
        pass

    # Shared-memory plane stats when installed (plasma stats role).
    try:
        plane = getattr(w, "shm_plane", None)
        if plane is not None:
            st = plane.store.stats()
            items = st.items() if isinstance(st, dict) else (
                (f, getattr(st, f)) for f in dir(st)
                if not f.startswith("_"))
            for field, val in items:
                if isinstance(val, (int, float)):
                    _gauge(f"ray_tpu_shm_{field}",
                           f"Shared-memory store {field}").set(
                        float(val))
    except Exception:
        pass
