"""Built-in runtime metrics (the reference's canonical stats).

Reference: `src/ray/stats/metric_defs.cc` — STATS_tasks / STATS_actors /
scheduler / object-store gauges exported alongside user metrics. Here
the same canonical series are computed ON EXPORT from live runtime state
(task-event buffer, actor registry, memory store, resources), so there's
no bookkeeping on the hot path; `collect_runtime_metrics()` refreshes
the gauges and the Prometheus endpoint calls it before rendering.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

from ray_tpu.util.metrics import Gauge

_gauges: Dict[str, Gauge] = {}
_prev_tags: Dict[str, set] = {}

# Library-side stats sources (serve ingress, …). The core exporter must
# not import upward into library packages (raylint R3), so libraries
# register a provider here at import time instead: ``provider() ->
# Optional[Dict[key, number]]`` plus a key -> (gauge_name, description)
# series map. A provider returning None contributes nothing this scrape.
_EXT_PROVIDERS: Dict[str, Tuple[Callable, Dict[str, Tuple[str, str]]]] = {}


def register_stats_provider(name: str, provider: Callable,
                            series: Dict[str, Tuple[str, str]]) -> None:
    _EXT_PROVIDERS[name] = (provider, series)


def _collect_ext_providers() -> None:
    for provider, series in list(_EXT_PROVIDERS.values()):
        try:
            stats = provider()
        except Exception:
            continue
        if stats is None:
            continue
        for key, (gauge_name, desc) in series.items():
            _gauge(gauge_name, desc).set(float(stats.get(key, 0)))


def _gauge(name: str, desc: str, tag_keys=()) -> Gauge:
    g = _gauges.get(name)
    if g is None:
        g = _gauges[name] = Gauge(name, desc, tag_keys=tag_keys)
    return g


def _set_series(name: str, desc: str, tag_key: str,
                values: Dict[str, float]) -> None:
    """Set a tagged gauge from a fresh snapshot, zeroing series whose
    tag vanished (a state with no members must read 0, not its last
    nonzero value — and a fresh session must not export the previous
    cluster's counts)."""
    g = _gauge(name, desc, tag_keys=(tag_key,))
    current = set(values)
    for stale in _prev_tags.get(name, set()) - current:
        g.set(0.0, tags={tag_key: stale})
    for tag, v in values.items():
        g.set(float(v), tags={tag_key: tag})
    _prev_tags[name] = current


def _collect_fastpath_stats() -> None:
    """Fold the lock-free fast-path stats (`_private/perf_stats.py` —
    batcher queue delay/flush size, submit→start latency, intern hit
    rate, SQLite group-commit latency, wait wake-ups, serve route
    latencies) into the registry as gauges: distributions export
    ``_p50``/``_p95``/``_count``/``_sum`` series, counters export
    ``_total``. Computed only here, on scrape — the hot paths pay two
    integer adds per observation, nothing more."""
    from ray_tpu._private import perf_stats

    for name, tags, stat in perf_stats.stats_items():
        tag_keys = tuple(k for k, _ in tags)
        tag_dict = dict(tags) or None
        if isinstance(stat, perf_stats.Counter):
            _gauge(f"ray_tpu_{name}_total",
                   f"fast-path counter {name}",
                   tag_keys=tag_keys).set(float(stat.value),
                                          tags=tag_dict)
            continue
        base = f"ray_tpu_{name}"
        _gauge(f"{base}_p50", f"fast-path {name} p50",
               tag_keys=tag_keys).set(stat.quantile(0.5), tags=tag_dict)
        _gauge(f"{base}_p95", f"fast-path {name} p95",
               tag_keys=tag_keys).set(stat.quantile(0.95), tags=tag_dict)
        _gauge(f"{base}_count", f"fast-path {name} observations",
               tag_keys=tag_keys).set(float(stat.total), tags=tag_dict)
        _gauge(f"{base}_sum", f"fast-path {name} sum",
               tag_keys=tag_keys).set(stat.sum, tags=tag_dict)


def collect_runtime_metrics() -> None:
    """Refresh the canonical runtime gauges from live state. Cheap
    (reads in-process tables); safe to call on every scrape."""
    from ray_tpu._private import worker as worker_mod

    try:
        _collect_fastpath_stats()
    except Exception:
        pass
    _collect_ext_providers()

    w = worker_mod.global_worker_or_none()
    if w is None:
        return

    # Tasks by state (reference STATS_tasks).
    by_state: Dict[str, float] = {}
    try:
        for ev in w.task_events.list_events():
            by_state[ev.state] = by_state.get(ev.state, 0) + 1
    except Exception:
        pass
    _set_series("ray_tpu_tasks", "Tasks by state", "state", by_state)

    # Actors by state (reference STATS_actors).
    try:
        actors = getattr(w.backend, "_actors", {})
        a_by_state: Dict[str, float] = {}
        for actor in list(actors.values()):
            a_by_state[actor.state] = a_by_state.get(actor.state, 0) + 1
        _set_series("ray_tpu_actors", "Actors by state", "state",
                    a_by_state)
    except Exception:
        pass

    # Object store occupancy (reference object_store_memory stats).
    try:
        store = w.memory_store
        with store._lock:
            entries = list(store._entries.values())
        n_objects = len(entries)
        n_bytes = float(sum(e.size or 0 for e in entries))
        _gauge("ray_tpu_object_store_objects",
               "Objects resident in the in-process store").set(
            float(n_objects))
        _gauge("ray_tpu_object_store_bytes",
               "Estimated bytes resident in the in-process store").set(
            n_bytes)
        spilled = sum(1 for e in entries if e.spilled_url)
        _gauge("ray_tpu_object_store_spilled_objects",
               "Objects currently spilled to external storage").set(
            float(spilled))
    except Exception:
        pass

    # Resource slots (reference scheduler resource gauges).
    try:
        res = w.backend.resources
        _set_series("ray_tpu_resources_total", "Total node resources",
                    "resource", dict(res.total))
        _set_series("ray_tpu_resources_available",
                    "Available node resources", "resource",
                    dict(res.available))
    except Exception:
        pass

    # Shared-memory plane stats when installed (plasma stats role).
    try:
        plane = getattr(w, "shm_plane", None)
        if plane is not None:
            st = plane.store.stats()
            items = st.items() if isinstance(st, dict) else (
                (f, getattr(st, f)) for f in dir(st)
                if not f.startswith("_"))
            for field, val in items:
                if isinstance(val, (int, float)):
                    _gauge(f"ray_tpu_shm_{field}",
                           f"Shared-memory store {field}").set(
                        float(val))
    except Exception:
        pass
