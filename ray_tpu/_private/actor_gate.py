"""Actor restart gate: caller-visible replay-or-reject semantics.

Role-equivalent to the reference's actor fault-tolerance contract
(`gcs_actor_manager.h` restart FSM + `direct_actor_task_submitter.h`
client-side queueing): when an actor's node dies, the actor transitions
ALIVE → RESTARTING (budget permitting) → ALIVE, or → DEAD when
``max_restarts`` is exhausted, and every call observes a *defined*
outcome keyed to its own ``max_task_retries``:

- a call **in flight** on the dying node replays against the restarted
  actor when it has retry budget (decrementing it), else rejects with
  an error naming the restart state and the remaining budget;
- a call **submitted during the restart window** parks (bounded by
  ``actor_restart_timeout_s``) and dispatches to the replacement when
  it has retry budget, else rejects immediately;
- a call against a DEAD (budget-exhausted) actor fails fast with an
  ``ActorDiedError`` naming the exhausted budget — it must never fall
  through to a backend that silently drops it.

This class is pure decision state — no RPC, no worker, no threads — so
the bounded model checker (`tools/raymc` ``actor_restart`` scenario)
can prove the contract over every interleaving of callers, node death,
and restart completion at small scope; ``ClusterHead`` wires the
decisions to real dispatch/park/fail effects.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from ray_tpu._private import sanitize_hooks


class ActorRestartState:
    ALIVE = "ALIVE"
    RESTARTING = "RESTARTING"
    DEAD = "DEAD"


class ActorRestartGate:
    """Per-head actor restart FSM + per-call replay-or-reject policy."""

    def __init__(self):
        self._lock = threading.Lock()
        # Parked callers wait on state transitions (ready / mark_dead /
        # rollback) instead of busy-polling.
        self._changed = threading.Condition(self._lock)
        self._state: Dict[bytes, str] = {}
        self._budget: Dict[bytes, int] = {}   # restarts left; -1 = inf
        self._max_restarts: Dict[bytes, int] = {}
        self._cause: Dict[bytes, str] = {}    # DEAD tombstone cause

    # -- registration / introspection -----------------------------------

    def register(self, actor_id: bytes, max_restarts: int,
                 used: int = 0) -> None:
        """First sighting of an actor creation: seed budget + state.
        Idempotent — a resubmitted creation spec must not reset a
        partially-consumed budget. ``used`` is the consumed-restart
        count carried on a node's re-register report: a FRESH gate
        (head failover) seeds ``max_restarts - used``, so budgets
        survive the failover instead of resetting (ROADMAP FT gap c).
        An actor re-reported with its whole budget spent registers at 0
        left — alive now, tombstoned on its next death."""
        sanitize_hooks.spec_op("spec.actor.register", "call", self,
                               (actor_id, max_restarts, used))
        with self._lock:
            if actor_id not in self._state:
                self._state[actor_id] = ActorRestartState.ALIVE
                budget = max_restarts
                if max_restarts >= 0 and used > 0:
                    budget = max(0, max_restarts - used)
                self._budget[actor_id] = budget
                self._max_restarts[actor_id] = max_restarts
        sanitize_hooks.spec_op("spec.actor.register", "ret", self,
                               actor_id)

    def state(self, actor_id: bytes) -> Optional[str]:
        with self._lock:
            return self._state.get(actor_id)

    def restarts_left(self, actor_id: bytes) -> int:
        with self._lock:
            return self._budget.get(actor_id, 0)

    def death_cause(self, actor_id: bytes) -> str:
        with self._lock:
            return self._cause.get(actor_id, "")

    def _budget_desc_locked(self, actor_id: bytes) -> str:
        left = self._budget.get(actor_id, 0)
        mx = self._max_restarts.get(actor_id, 0)
        if left == -1:
            return "max_restarts=-1 (infinite)"
        return f"{left} of max_restarts={mx} left"

    # -- restart FSM -----------------------------------------------------

    def begin_restart(self, actor_id: bytes, reason: str) -> bool:
        """The actor's host died. Returns True when a restart was
        started (budget consumed, state → RESTARTING); False when the
        budget is exhausted (state → DEAD, tombstoned with a cause
        naming the budget)."""
        sanitize_hooks.spec_op("spec.actor.restart", "call", self,
                               actor_id)
        sanitize_hooks.sched_point("actor.restart.begin")
        started = False
        with self._lock:
            try:
                if self._state.get(actor_id) == ActorRestartState.DEAD:
                    started = False
                    return False
                left = self._budget.get(actor_id, 0)
                if left == 0:
                    mx = self._max_restarts.get(actor_id, 0)
                    self._state[actor_id] = ActorRestartState.DEAD
                    self._cause[actor_id] = (
                        f"{reason}; restart budget exhausted "
                        f"(max_restarts={mx}, 0 restarts left)")
                    started = False
                    return False
                if left > 0:
                    self._budget[actor_id] = left - 1
                self._state[actor_id] = ActorRestartState.RESTARTING
                started = True
                return True
            finally:
                self._changed.notify_all()
                sanitize_hooks.spec_op("spec.actor.restart", "ret", self,
                                       (actor_id, started))

    def ready(self, actor_id: bytes) -> None:
        """The replacement registered a live location: parked callers
        may dispatch now."""
        sanitize_hooks.spec_op("spec.actor.ready", "call", self, actor_id)
        sanitize_hooks.sched_point("actor.restart.ready")
        with self._lock:
            if self._state.get(actor_id) == ActorRestartState.RESTARTING:
                self._state[actor_id] = ActorRestartState.ALIVE
            self._changed.notify_all()
        sanitize_hooks.spec_op("spec.actor.ready", "ret", self, actor_id)

    def rollback_ready(self, actor_id: bytes) -> None:
        """A location gain was unwound (the send to the chosen node
        failed and the directory entry was popped): an ALIVE flip must
        not stand with no live location, or parked/new calls fall
        through to a backend that has never heard of the actor. The
        re-dispatch (or queue/fail path) will flip it again."""
        sanitize_hooks.spec_op("spec.actor.rollback", "call", self,
                               actor_id)
        with self._lock:
            if self._state.get(actor_id) == ActorRestartState.ALIVE:
                self._state[actor_id] = ActorRestartState.RESTARTING
            self._changed.notify_all()
        sanitize_hooks.spec_op("spec.actor.rollback", "ret", self,
                               actor_id)

    def mark_dead(self, actor_id: bytes, cause: str) -> None:
        sanitize_hooks.spec_op("spec.actor.dead", "call", self, actor_id)
        with self._lock:
            self._state[actor_id] = ActorRestartState.DEAD
            self._cause.setdefault(actor_id, cause)
            self._changed.notify_all()
        sanitize_hooks.spec_op("spec.actor.dead", "ret", self, actor_id)

    def wait_change(self, timeout_s: float) -> None:
        """Park until some actor's gate state changes (bounded): the
        wake signal for parked-call waiters — no busy polling."""
        with self._changed:
            self._changed.wait(timeout_s)

    # -- per-call decisions ----------------------------------------------
    #
    # Both take effect callbacks rather than returning verdicts: the
    # decision and its effect wiring are ONE product seam — ClusterHead
    # passes real dispatch/park/fail closures, the model checker passes
    # counters, and both exercise the same branch structure.

    def route_call(self, spec, dispatch: Callable, park: Callable,
                   fail: Callable) -> None:
        """Submission-time decision for an actor call with no live
        location. ``dispatch()`` is never called here (there is no
        node); ``park(spec)`` queues the call for the restart window;
        ``fail(spec, msg, dead)`` rejects it (``dead``: tombstone vs
        mid-restart rejection)."""
        del dispatch  # routing without a location never dispatches
        aid = spec.actor_id.binary()
        sanitize_hooks.spec_op(
            "spec.actor.route", "call", self,
            (aid, spec.max_retries, getattr(spec, "attempt", 0)))
        sanitize_hooks.sched_point("actor.route")
        with self._lock:
            state = self._state.get(aid)
            msg = self._reject_msg_locked(spec, state)
        verdict = "park" if msg is None else (
            "dead" if state == ActorRestartState.DEAD else "reject")
        sanitize_hooks.spec_op("spec.actor.route", "ret", self,
                               (aid, verdict))
        if msg is None:
            park(spec)
        else:
            fail(spec, msg, state == ActorRestartState.DEAD)

    def recover_call(self, spec, resubmit: Callable,
                     fail: Callable) -> None:
        """Replay-or-reject for a call that was IN FLIGHT on a node
        that died. A replay consumes one unit of the call's own
        ``max_task_retries`` budget (``spec.max_retries``); a call with
        none left — or whose actor is DEAD — rejects with an error
        naming the state and the remaining budgets."""
        aid = spec.actor_id.binary()
        sanitize_hooks.spec_op("spec.actor.replay", "call", self,
                               (aid, spec.max_retries))
        sanitize_hooks.sched_point("actor.replay")
        with self._lock:
            state = self._state.get(aid)
            if state == ActorRestartState.DEAD:
                msg = (f"call {spec.describe()} was in flight when the "
                       f"actor died: {self._cause.get(aid, 'dead')}")
            elif spec.max_retries == 0:
                msg = (f"call {spec.describe()} was in flight when its "
                       f"node died and has no retries left "
                       f"(max_task_retries budget exhausted: 0 left); "
                       f"actor is {state or 'UNKNOWN'} "
                       f"({self._budget_desc_locked(aid)})")
            else:
                # The replay consumes one retry NOW; attempt marks the
                # spec as replay-authorized so the routing decision it
                # is about to re-enter parks it instead of re-judging
                # the (already-charged) budget.
                if spec.max_retries > 0:
                    spec.max_retries -= 1
                spec.attempt = getattr(spec, "attempt", 0) + 1
                msg = None
        verdict = "resubmit" if msg is None else (
            "dead" if state == ActorRestartState.DEAD else "reject")
        sanitize_hooks.spec_op("spec.actor.replay", "ret", self,
                               (aid, verdict))
        if msg is None:
            resubmit(spec)
        else:
            fail(spec, msg, state == ActorRestartState.DEAD)

    def _reject_msg_locked(self, spec, state) -> Optional[str]:
        """None = park; else the rejection message. A call that races a
        completed restart (state already ALIVE again) parks — the park
        waiter dispatches it immediately — rather than spuriously
        rejecting a call against a healthy actor."""
        aid = spec.actor_id.binary()
        if state == ActorRestartState.DEAD:
            return (f"call {spec.describe()} rejected: "
                    f"{self._cause.get(aid, 'actor is dead')}")
        if state == ActorRestartState.RESTARTING and \
                spec.max_retries == 0 and \
                getattr(spec, "attempt", 0) == 0:
            # attempt > 0 = a replay recover_call already authorized
            # (and charged) — it must park for the replacement, not be
            # re-judged against its now-consumed budget.
            return (f"call {spec.describe()} rejected: actor is "
                    f"RESTARTING and the call has no retry budget to "
                    f"ride the restart window (max_task_retries=0; "
                    f"actor restarts: {self._budget_desc_locked(aid)})")
        return None
