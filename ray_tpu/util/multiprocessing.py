"""multiprocessing.Pool-compatible shim over tasks.

Reference: `python/ray/util/multiprocessing/` — drop-in Pool whose workers
are remote tasks instead of forked processes.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


@ray_tpu.remote
def _invoke(fn, args, kwargs):
    return fn(*args, **(kwargs or {}))


class AsyncResult:
    def __init__(self, refs, single: bool):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None):
        vals = ray_tpu.get(self._refs, timeout=timeout)
        return vals[0] if self._single else vals

    def wait(self, timeout: Optional[float] = None):
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        try:
            self.get(timeout=0)
            return True
        except Exception:
            return False


class Pool:
    def __init__(self, processes: Optional[int] = None, *args, **kwargs):
        ray_tpu.init(ignore_reinit_error=True)
        self._processes = processes

    def apply(self, fn: Callable, args: tuple = (), kwds: dict = None):
        return ray_tpu.get(_invoke.remote(fn, args, kwds))

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwds: dict = None) -> AsyncResult:
        return AsyncResult([_invoke.remote(fn, args, kwds)], single=True)

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return ray_tpu.get([_invoke.remote(fn, (x,), None)
                            for x in iterable])

    def map_async(self, fn: Callable, iterable: Iterable,
                  chunksize=None) -> AsyncResult:
        return AsyncResult([_invoke.remote(fn, (x,), None)
                            for x in iterable], single=False)

    def starmap(self, fn: Callable, iterable: Iterable[tuple],
                chunksize=None) -> List[Any]:
        return ray_tpu.get([_invoke.remote(fn, tuple(args), None)
                            for args in iterable])

    def imap(self, fn: Callable, iterable: Iterable, chunksize=None):
        refs = [_invoke.remote(fn, (x,), None) for x in iterable]
        for r in refs:
            yield ray_tpu.get(r)

    def imap_unordered(self, fn: Callable, iterable: Iterable,
                       chunksize=None):
        refs = [_invoke.remote(fn, (x,), None) for x in iterable]
        pending = list(refs)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            yield ray_tpu.get(ready[0])

    def close(self):
        pass

    def terminate(self):
        pass

    def join(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
        return False
