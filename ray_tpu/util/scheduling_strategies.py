"""Public scheduling strategies (reference: ``python/ray/util/scheduling_strategies.py``)."""

from ray_tpu._private.task_spec import (  # noqa: F401
    DefaultSchedulingStrategy,
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SchedulingStrategy,
    SpreadSchedulingStrategy,
)

__all__ = [
    "SchedulingStrategy",
    "DefaultSchedulingStrategy",
    "SpreadSchedulingStrategy",
    "NodeAffinitySchedulingStrategy",
    "PlacementGroupSchedulingStrategy",
]
