"""Serializability inspection (reference `ray.util.check_serialize`)."""

from __future__ import annotations

import inspect
import pickle
from typing import Any, Set, Tuple


def inspect_serializability(obj: Any, name: str = None,
                            depth: int = 3) -> Tuple[bool, Set[str]]:
    """Try to pickle `obj`; on failure, walk closures/attributes to find
    the offending members. Returns (serializable, failure_set)."""
    failures: Set[str] = set()
    name = name or getattr(obj, "__name__", repr(obj)[:40])
    ok = _check(obj, name, depth, failures)
    return ok, failures


def _check(obj, name, depth, failures) -> bool:
    try:
        pickle.dumps(obj)
        return True
    except Exception:
        pass
    if depth <= 0:
        failures.add(name)
        return False
    found_inner = False
    if inspect.isfunction(obj) and obj.__closure__:
        for var, cell in zip(obj.__code__.co_freevars, obj.__closure__):
            try:
                inner = cell.cell_contents
            except ValueError:
                continue
            if not _check(inner, f"{name}.<closure:{var}>", depth - 1,
                          failures):
                found_inner = True
    members = getattr(obj, "__dict__", None)
    if isinstance(members, dict):
        for attr, value in list(members.items())[:50]:
            if not _check(value, f"{name}.{attr}", depth - 1, failures):
                found_inner = True
    if not found_inner:
        failures.add(name)
    return False
