"""Custom serializer registry.

Reference: `ray.util.register_serializer` /
`_private/serialization.py` SerializationContext custom-type hooks — a
process-wide mapping from a class to (serializer, deserializer) used
whenever that class crosses a process boundary (task args, returns,
puts). Implemented over `copyreg` dispatch, which both pickle and
cloudpickle honour, so every wire path (typed-wire Opaque sections, shm
plane, specs) picks it up with no per-path plumbing.
"""

from __future__ import annotations

import copyreg
from typing import Any, Callable, Dict, Tuple

_REGISTRY: Dict[type, Tuple[Callable, Callable]] = {}


def _reconstruct(cls: type, serializer: Callable, deserializer: Callable,
                 payload: Any):
    # Self-propagating: deserializing an instance in another process
    # (cluster node, spawned worker) installs the serializer THERE too,
    # so that process can send instances onward / back. A process that
    # creates instances without ever receiving one must call
    # register_serializer itself (e.g. at module import in the task's
    # code), same as the reference.
    if cls not in _REGISTRY:
        register_serializer(cls, serializer=serializer,
                            deserializer=deserializer)
    return deserializer(payload)


def register_serializer(cls: type, *, serializer: Callable[[Any], Any],
                        deserializer: Callable[[Any], Any]) -> None:
    """Serialize instances of `cls` as `serializer(obj)` (any picklable
    payload); reconstruct with `deserializer(payload)`."""
    if not isinstance(cls, type):
        raise TypeError(f"cls must be a class, got {cls!r}")

    def reduce_fn(obj):
        return (_reconstruct,
                (cls, serializer, deserializer, serializer(obj)))

    _REGISTRY[cls] = (serializer, deserializer)
    copyreg.pickle(cls, reduce_fn)


def deregister_serializer(cls: type) -> None:
    _REGISTRY.pop(cls, None)
    copyreg.dispatch_table.pop(cls, None)
