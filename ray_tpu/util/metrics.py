"""User-defined metrics: Counter / Gauge / Histogram.

Reference: `python/ray/util/metrics.py` → C++ OpenCensus pipeline. Here
metrics aggregate in a process-global registry with tag support and a
Prometheus-exposition dump (`export_prometheus`), which the dashboard/
metrics agent scrapes or writes out.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

_registry_lock = threading.Lock()
_registry: Dict[str, "Metric"] = {}


class Metric:
    kind = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry[name] = self

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple:
        merged = {**self._default_tags, **(tags or {})}
        return tuple(sorted(merged.items()))


class Counter(Metric):
    kind = "counter"

    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("counters only increase")
        k = self._key(tags)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value

    def get(self, tags=None) -> float:
        return self._values.get(self._key(tags), 0.0)


class Gauge(Metric):
    kind = "gauge"

    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        with self._lock:
            self._values[self._key(tags)] = float(value)

    def get(self, tags=None) -> float:
        return self._values.get(self._key(tags), 0.0)


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name, description="",
                 boundaries: Sequence[float] = (), tag_keys=()):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries) or [
            0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10]
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._totals: Dict[Tuple, int] = {}

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None):
        k = self._key(tags)
        with self._lock:
            counts = self._counts.setdefault(
                k, [0] * (len(self.boundaries) + 1))
            counts[bisect.bisect_left(self.boundaries, value)] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._totals[k] = self._totals.get(k, 0) + 1

    def get(self, tags=None) -> dict:
        k = self._key(tags)
        return {"count": self._totals.get(k, 0),
                "sum": self._sums.get(k, 0.0),
                "buckets": list(self._counts.get(
                    k, [0] * (len(self.boundaries) + 1)))}


def _fmt_tags(key: Tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


def export_prometheus() -> str:
    """Prometheus text exposition of every registered metric (canonical
    runtime gauges refreshed first — `_private/runtime_metrics.py`)."""
    try:
        from ray_tpu._private.runtime_metrics import (
            collect_runtime_metrics,
        )

        collect_runtime_metrics()
    except Exception:  # noqa: BLE001 — user metrics still export
        pass
    lines: List[str] = []
    with _registry_lock:
        metrics = list(_registry.values())
    for m in metrics:
        lines.append(f"# HELP {m.name} {m.description}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        if isinstance(m, (Counter, Gauge)):
            for k, v in m._values.items():
                lines.append(f"{m.name}{_fmt_tags(k)} {v}")
        elif isinstance(m, Histogram):
            for k, counts in m._counts.items():
                acc = 0
                for b, c in zip(m.boundaries, counts):
                    acc += c
                    tags = dict(k)
                    tags["le"] = str(b)
                    lines.append(
                        f"{m.name}_bucket{_fmt_tags(tuple(sorted(tags.items())))} {acc}")
                tags = dict(k)
                tags["le"] = "+Inf"
                lines.append(
                    f"{m.name}_bucket{_fmt_tags(tuple(sorted(tags.items())))} {m._totals[k]}")
                lines.append(f"{m.name}_sum{_fmt_tags(k)} {m._sums[k]}")
                lines.append(f"{m.name}_count{_fmt_tags(k)} {m._totals[k]}")
    return "\n".join(lines) + "\n"
