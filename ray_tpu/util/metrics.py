"""User-defined metrics: Counter / Gauge / Histogram.

Reference: `python/ray/util/metrics.py` → C++ OpenCensus pipeline. Here
metrics aggregate in a process-global registry with tag support and a
Prometheus-exposition dump (`export_prometheus`), which the dashboard/
metrics agent scrapes or writes out.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

_registry_lock = threading.Lock()
_registry: Dict[str, "Metric"] = {}


class Metric:
    kind = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry[name] = self

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple:
        merged = {**self._default_tags, **(tags or {})}
        return tuple(sorted(merged.items()))


class Counter(Metric):
    kind = "counter"

    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("counters only increase")
        k = self._key(tags)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value

    def get(self, tags=None) -> float:
        return self._values.get(self._key(tags), 0.0)


class Gauge(Metric):
    kind = "gauge"

    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        with self._lock:
            self._values[self._key(tags)] = float(value)

    def get(self, tags=None) -> float:
        return self._values.get(self._key(tags), 0.0)


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name, description="",
                 boundaries: Sequence[float] = (), tag_keys=()):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries) or [
            0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10]
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._totals: Dict[Tuple, int] = {}

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None):
        k = self._key(tags)
        with self._lock:
            counts = self._counts.setdefault(
                k, [0] * (len(self.boundaries) + 1))
            counts[bisect.bisect_left(self.boundaries, value)] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._totals[k] = self._totals.get(k, 0) + 1

    def get(self, tags=None) -> dict:
        k = self._key(tags)
        return {"count": self._totals.get(k, 0),
                "sum": self._sums.get(k, 0.0),
                "buckets": list(self._counts.get(
                    k, [0] * (len(self.boundaries) + 1)))}


def reset_values() -> None:
    """Zero every registered metric's recorded values IN PLACE,
    keeping registrations (metrics are interned by name — dropping
    registry entries would orphan the instances call sites hold, so
    recordings would keep landing in objects the exposition no longer
    sees). The reset-capable API raylint R7 requires of process-global
    registries; tests use it to start from a clean exposition."""
    with _registry_lock:
        metrics = list(_registry.values())
    for m in metrics:
        with m._lock:
            for attr in ("_values", "_counts", "_sums", "_totals"):
                d = getattr(m, attr, None)
                if d is not None:
                    d.clear()


# Prometheus text exposition format 0.0.4 — scrape endpoints return
# this Content-Type per the exposition spec.
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4"


def _fmt_tags(key: Tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


def snapshot_registry() -> dict:
    """Plain-data snapshot of the whole registry — wire-encodable
    (str/float/list only), so cluster nodes ship it to the head's
    aggregator and the dashboard merges snapshots from every node into
    one exposition. Shape per metric::

        {name: {"kind", "description", "boundaries"?, "series": [...]}}

    Counter/gauge series entries are ``[tag_pairs, value]``; histogram
    entries are ``[tag_pairs, bucket_counts, sum, count]`` where
    ``tag_pairs`` is ``[[k, v], ...]`` sorted by key.
    """
    with _registry_lock:
        metrics = list(_registry.values())
    snap: dict = {}
    for m in metrics:
        entry: dict = {"kind": m.kind, "description": m.description}
        if isinstance(m, (Counter, Gauge)):
            with m._lock:
                entry["series"] = [
                    [[list(kv) for kv in k], v]
                    for k, v in m._values.items()]
        elif isinstance(m, Histogram):
            entry["boundaries"] = list(m.boundaries)
            with m._lock:
                entry["series"] = [
                    [[list(kv) for kv in k], list(counts),
                     m._sums.get(k, 0.0), m._totals.get(k, 0)]
                    for k, counts in m._counts.items()]
        else:
            entry["series"] = []
        snap[m.name] = entry
    return snap


def _render_series(lines: List[str], name: str, entry: dict,
                   extra_tags: Optional[dict]) -> None:
    extra = tuple(sorted((extra_tags or {}).items()))
    if entry["kind"] in ("counter", "gauge", "untyped"):
        for tag_pairs, v in entry.get("series", []):
            key = tuple(sorted(
                tuple(kv) for kv in list(tag_pairs) + [list(t) for t
                                                       in extra]))
            lines.append(f"{name}{_fmt_tags(key)} {v}")
        return
    boundaries = entry.get("boundaries", [])
    for tag_pairs, counts, total_sum, total in entry.get("series", []):
        base = {k: v for k, v in tag_pairs}
        base.update(dict(extra))
        acc = 0
        for b, c in zip(boundaries, counts):
            acc += c
            tags = dict(base)
            tags["le"] = str(b)
            lines.append(
                f"{name}_bucket{_fmt_tags(tuple(sorted(tags.items())))}"
                f" {acc}")
        tags = dict(base)
        tags["le"] = "+Inf"
        lines.append(
            f"{name}_bucket{_fmt_tags(tuple(sorted(tags.items())))}"
            f" {total}")
        key = tuple(sorted(base.items()))
        lines.append(f"{name}_sum{_fmt_tags(key)} {total_sum}")
        lines.append(f"{name}_count{_fmt_tags(key)} {total}")


def render_prometheus(snapshots) -> str:
    """Merge registry snapshots into one Prometheus text exposition.

    ``snapshots`` is ``[(snapshot, extra_tags_or_None), ...]`` — the
    dashboard passes the head's snapshot untagged plus one
    ``{"node": node_id}``-tagged snapshot per cluster node, so every
    node's series land under shared metric names with a ``node`` label
    distinguishing them. HELP/TYPE headers are emitted once per name.
    """
    by_name: "dict[str, list]" = {}
    order: List[str] = []
    for snap, extra in snapshots:
        for name, entry in snap.items():
            if name not in by_name:
                by_name[name] = []
                order.append(name)
            by_name[name].append((entry, extra))
    lines: List[str] = []
    for name in order:
        entries = by_name[name]
        lines.append(f"# HELP {name} {entries[0][0]['description']}")
        lines.append(f"# TYPE {name} {entries[0][0]['kind']}")
        for entry, extra in entries:
            _render_series(lines, name, entry, extra)
    return "\n".join(lines) + "\n"


def export_prometheus() -> str:
    """Prometheus text exposition of every registered metric (canonical
    runtime gauges refreshed first — `_private/runtime_metrics.py`)."""
    try:
        from ray_tpu._private.runtime_metrics import (
            collect_runtime_metrics,
        )

        collect_runtime_metrics()
    except Exception:  # noqa: BLE001 — user metrics still export
        pass
    return render_prometheus([(snapshot_registry(), None)])
