"""Accelerator type constants — TPU-first.

Reference: `python/ray/util/accelerators/accelerators.py` (NVIDIA-only in
the snapshot). Here TPU generations are first-class, with chip/HBM specs
the scheduler and mesh heuristics can consult; NVIDIA constants retained
for API compatibility.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

# TPU generations (per-chip figures; bf16 peak)
TPU_V4 = "TPU-V4"
TPU_V5E = "TPU-V5E"
TPU_V5P = "TPU-V5P"
TPU_V6E = "TPU-V6E"

# Reference-compat GPU constants
NVIDIA_TESLA_V100 = "V100"
NVIDIA_TESLA_P100 = "P100"
NVIDIA_TESLA_T4 = "T4"
NVIDIA_TESLA_A100 = "A100"
NVIDIA_A100_40G = "A100-40G"
NVIDIA_A100_80G = "A100-80G"
NVIDIA_H100 = "H100"


@dataclass(frozen=True)
class TPUChipSpec:
    name: str
    hbm_bytes: int
    peak_bf16_flops: float
    ici_bandwidth_gbps: float  # per link, one direction


TPU_SPECS: Dict[str, TPUChipSpec] = {
    TPU_V4: TPUChipSpec(TPU_V4, 32 * 2**30, 275e12, 50),
    TPU_V5E: TPUChipSpec(TPU_V5E, 16 * 2**30, 197e12, 50),
    TPU_V5P: TPUChipSpec(TPU_V5P, 95 * 2**30, 459e12, 100),
    TPU_V6E: TPUChipSpec(TPU_V6E, 32 * 2**30, 918e12, 100),
}


def detect_tpu_type() -> str:
    """Best-effort generation detection on this host."""
    import os

    env = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    mapping = {"v4": TPU_V4, "v5e": TPU_V5E, "v5p": TPU_V5P,
               "v6e": TPU_V6E}
    if env in mapping:
        return mapping[env]
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
        for key, val in mapping.items():
            if key in kind:
                return val
        if "v5 lite" in kind or "v5lite" in kind:
            return TPU_V5E
    except Exception:
        pass
    return TPU_V5E


def chip_spec(name: str = None) -> TPUChipSpec:
    return TPU_SPECS[name or detect_tpu_type()]
