"""ActorPool: load-balance tasks over a fixed set of actors.

Reference: `python/ray/util/actor_pool.py` — same surface (map,
map_unordered, submit/get_next, push/pop_idle), plus `map_refs` used by the
data layer to stream ObjectRefs through a pool without fetching values.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List

import ray_tpu


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor: dict = {}
        self._index_to_future: dict = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending: List = []

    # -- core ------------------------------------------------------------

    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """fn(actor, value) -> ObjectRef; queues if no actor is idle."""
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = actor
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._index_to_future) or bool(self._pending)

    def has_free(self) -> bool:
        return bool(self._idle) and not self._pending

    def _drain_pending(self):
        while self._pending and self._idle:
            fn, value = self._pending.pop(0)
            self.submit(fn, value)

    def get_next(self, timeout: float = None):
        """Next result in submission order."""
        if self._next_return_index not in self._index_to_future:
            if not self.has_next():
                raise StopIteration("no pending results")
            self._drain_pending()
        future = self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        value = ray_tpu.get(future, timeout=timeout)
        self._return_actor(future)
        return value

    def get_next_unordered(self, timeout: float = None):
        if not self._index_to_future and not self._pending:
            raise StopIteration("no pending results")
        self._drain_pending()
        ready, _ = ray_tpu.wait(list(self._index_to_future.values()),
                                num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("timed out waiting for result")
        future = ready[0]
        for idx, f in list(self._index_to_future.items()):
            if f == future:
                del self._index_to_future[idx]
                break
        value = ray_tpu.get(future)
        self._return_actor(future)
        return value

    def _return_actor(self, future):
        actor = self._future_to_actor.pop(future, None)
        if actor is not None:
            self._idle.append(actor)
            self._drain_pending()

    # -- bulk helpers ----------------------------------------------------

    def map(self, fn: Callable[[Any, Any], Any],
            values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def map_refs(self, fn: Callable[[Any, Any], Any],
                 refs: Iterable[Any]) -> List[Any]:
        """Run fn(actor, ref) for each ref, returning result *refs* in
        order (results stay in the object store)."""
        refs = list(refs)
        out: List[Any] = [None] * len(refs)
        submitted: dict = {}
        i = 0
        while i < len(refs) or submitted:
            while i < len(refs) and self._idle:
                actor = self._idle.pop()
                future = fn(actor, refs[i])
                submitted[future] = (i, actor)
                i += 1
            if submitted:
                ready, _ = ray_tpu.wait(list(submitted), num_returns=1)
                f = ready[0]
                idx, actor = submitted.pop(f)
                out[idx] = f
                self._idle.append(actor)
        return out

    # -- membership ------------------------------------------------------

    def push(self, actor: Any) -> None:
        self._idle.append(actor)
        self._drain_pending()

    def pop_idle(self):
        return self._idle.pop() if self._idle else None
