from ray_tpu.util.scheduling_strategies import (  # noqa: F401
    DefaultSchedulingStrategy,
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SchedulingStrategy,
    SpreadSchedulingStrategy,
)
