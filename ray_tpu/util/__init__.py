from ray_tpu.util.scheduling_strategies import (  # noqa: F401
    DefaultSchedulingStrategy,
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SchedulingStrategy,
    SpreadSchedulingStrategy,
)
from ray_tpu.util.serialization import (  # noqa: F401
    deregister_serializer,
    register_serializer,
)
