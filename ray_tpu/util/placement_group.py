"""Placement groups: gang reservation of resource bundles.

Reference: `python/ray/util/placement_group.py:33,136` (API) and the
raylet-side 2PC reservation (`raylet/placement_group_resource_manager.h`).
Strategies PACK/SPREAD/STRICT_PACK/STRICT_SPREAD keep reference semantics;
the TPU extension is an optional ``ici_slice`` bundle label so STRICT_PACK
groups can demand a contiguous ICI sub-slice (chips that neighbour on the
torus) rather than any N chips — the gang-scheduling constraint GPUs never
needed (SURVEY.md §7 "hard parts").

On the single-node in-process backend, reservation carves bundle pools out
of the node's ResourceSet atomically (all-or-nothing, the 2PC degenerate
case); the cluster backend will run prepare/commit across nodes on the
same interfaces.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from ray_tpu._private.ids import PlacementGroupID
from ray_tpu._private.resources import ResourceSet, to_milli
from ray_tpu._private import worker as worker_mod
from ray_tpu import exceptions as exc

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    """Handle to a reserved (or pending) group of bundles."""

    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]],
                 strategy: str, name: str = ""):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy
        self.name = name
        self._ready = threading.Event()
        self._failed: Optional[str] = None

    def ready(self):
        """Returns an ObjectRef resolving when reservation completes
        (reference returns a ref for `ray.get(pg.ready())`)."""
        import ray_tpu

        @ray_tpu.remote
        def _wait(pg_name, pg):
            pg.wait(timeout=60.0)
            return pg

        return _wait.options(num_cpus=0.001).remote(self.name, self)

    def wait(self, timeout: Optional[float] = None) -> bool:
        ok = self._ready.wait(timeout)
        if self._failed:
            raise exc.PlacementGroupSchedulingError(self._failed)
        return ok

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def __reduce__(self):
        # Handles are pass-by-reference: the receiving process resolves
        # by id from its registry, or (on a worker node that never saw
        # the creation) reconstructs a detached handle — the id and
        # bundle shape are all task routing needs.
        return (_lookup_pg, (self.id, self.bundle_specs, self.strategy,
                             self.name))


def _lookup_pg(pg_id, bundles=None, strategy="PACK", name=""):
    w = worker_mod.global_worker()
    table = w.gcs.placement_group_table()
    pg = table.get(pg_id)
    if pg is None:
        if bundles is None:
            raise exc.PlacementGroupSchedulingError(
                f"placement group {pg_id} not found")
        pg = PlacementGroup(pg_id, bundles, strategy, name)
        pg._ready.set()
    return pg


def placement_group(bundles: List[Dict[str, float]], *,
                    strategy: str = "PACK", name: str = "",
                    lifetime: Optional[str] = None,
                    ici_slice: Optional[str] = None) -> PlacementGroup:
    """Reserve bundles. Reference: `util/placement_group.py:33`.

    ``ici_slice`` (TPU extension): constrain every bundle to nodes of one
    contiguous ICI slice — a specific slice by label value, or ``"auto"``
    to let the scheduler pick any single slice whose nodes fit the group.
    Nodes advertise their slice via the ``ici_slice`` node label.
    """
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles:
        raise ValueError("placement group requires at least one bundle")
    for b in bundles:
        if not b or all(v == 0 for v in b.values()):
            raise ValueError(f"bundle must request resources: {b}")
    w = worker_mod.global_worker()
    pg = PlacementGroup(PlacementGroupID.from_random(), bundles, strategy,
                        name)
    pg.ici_slice = ici_slice
    w.gcs.register_placement_group(pg)
    backend = w.backend

    # Cluster mode: multi-node reservation through the head (2PC).
    head = getattr(w, "cluster_head", None)
    if head is not None and getattr(head, "nodes", None):
        threading.Thread(
            target=_cluster_reserve, args=(w, head, pg),
            kwargs={"ici_slice": ici_slice}, daemon=True).start()
        return pg

    # Single-node reservation: all bundles land on this node. STRICT_SPREAD
    # demands distinct nodes, which a single-node cluster cannot satisfy
    # unless there is exactly one bundle.
    if strategy == "STRICT_SPREAD" and len(bundles) > 1 and \
            len(w.gcs.nodes()) == 1:
        pg._failed = (
            "STRICT_SPREAD with multiple bundles cannot be satisfied on a "
            "single-node cluster")
        pg._ready.set()
        return pg

    milli = [to_milli(b) for b in bundles]
    # All-or-nothing: acquire every bundle from the node pool, then carve
    # per-bundle ResourceSets (the 2PC prepare+commit collapsed to one op).
    acquired = []
    ok = True
    for req in milli:
        if backend.resources.try_acquire(req):
            acquired.append(req)
        else:
            ok = False
            break
    if not ok:
        for req in acquired:
            backend.resources.release(req)
        # Leave pending; a retry loop waits for resources to free up.
        def _retry():
            deadline = time.monotonic() + 300
            while time.monotonic() < deadline:
                got = []
                done = True
                for req in milli:
                    if backend.resources.try_acquire(req):
                        got.append(req)
                    else:
                        done = False
                        break
                if done:
                    _commit(backend, pg, bundles)
                    return
                for req in got:
                    backend.resources.release(req)
                backend.resources.wait_for_change(timeout=0.2)
            pg._failed = "placement group reservation timed out"
            pg._ready.set()

        threading.Thread(target=_retry, daemon=True).start()
        return pg

    _commit(backend, pg, bundles)
    return pg


def _commit(backend, pg: PlacementGroup, bundles):
    for i, b in enumerate(bundles):
        backend.bundle_resources[(pg.id, i)] = ResourceSet(b)
    pg._ready.set()


# ---------------------------------------------------------------------------
# Cluster-mode reservation: 2PC prepare/commit across nodes.
# Reference: `gcs_placement_group_scheduler.h` (PreparePgBundles →
# CommitPgBundles, ReturnPgBundles on abort) with the PACK / SPREAD /
# STRICT_* placement policies of `bundle_scheduling_policy.h:82-109`.
# ---------------------------------------------------------------------------


class _Candidate:
    """A placement target: the head's local backend or a remote node."""

    def __init__(self, node_id, available_milli, labels):
        self.node_id = node_id          # None = the head itself
        self.avail = dict(available_milli)
        self.labels = labels or {}

    def fits(self, req) -> bool:
        return all(self.avail.get(k, 0) >= v for k, v in req.items())

    def take(self, req) -> None:
        for k, v in req.items():
            self.avail[k] = self.avail.get(k, 0) - v


def _candidates(w, head) -> List[_Candidate]:
    out = []
    local = w.backend.resources
    with local._cond:
        avail = dict(local._available)
    out.append(_Candidate(None, avail, {}))
    # Pushed resource view (ray_syncer role) — no per-reservation pings;
    # stale optimism is corrected by the prepare phase failing and the
    # reservation loop retrying.
    for record in list(head.nodes.values()):
        if not record.alive:
            continue
        milli = {k: int(v * 1000) for k, v in record.available.items()}
        out.append(_Candidate(record.node_id, milli, record.labels))
    return out


def _plan_bundles(candidates: List[_Candidate], milli: List[Dict[str, int]],
                  strategy: str) -> Optional[List[_Candidate]]:
    """Assign each bundle a candidate (simulated greedily on copies of
    the availability vectors); None if the strategy can't be satisfied."""
    if strategy == "STRICT_PACK":
        for cand in sorted(candidates, key=lambda c: -sum(c.avail.values())):
            trial = _Candidate(cand.node_id, cand.avail, cand.labels)
            if all(_take_if_fits(trial, req) for req in milli):
                return [cand] * len(milli)
        return None
    if strategy == "STRICT_SPREAD":
        if len(candidates) < len(milli):
            return None
        # Place the largest bundles first (greedy on distinct nodes is
        # only correct in decreasing-size order).
        order_b = sorted(range(len(milli)),
                         key=lambda i: -sum(milli[i].values()))
        chosen_by_idx: Dict[int, _Candidate] = {}
        used = set()
        for i in order_b:
            req = milli[i]
            picked = None
            for cand in sorted(candidates,
                               key=lambda c: -sum(c.avail.values())):
                if id(cand) in used or not cand.fits(req):
                    continue
                picked = cand
                break
            if picked is None:
                return None
            used.add(id(picked))
            chosen_by_idx[i] = picked
        return [chosen_by_idx[i] for i in range(len(milli))]
    # PACK: minimize node count — greedy first-fit onto already-used
    # nodes, opening a new one only when needed. SPREAD: round-robin
    # best-effort distinct.
    sims = [_Candidate(c.node_id, c.avail, c.labels) for c in candidates]
    by_sim = dict(zip(map(id, sims), candidates))
    chosen = []
    used: List[int] = []
    order = sorted(range(len(sims)),
                   key=lambda i: -sum(sims[i].avail.values()))
    rr = 0
    for req in milli:
        picked = None
        if strategy == "PACK":
            for idx in used:
                if sims[idx].fits(req):
                    picked = idx
                    break
            if picked is None:
                for idx in order:
                    if sims[idx].fits(req):
                        picked = idx
                        break
        else:  # SPREAD
            for attempt in range(len(sims)):
                idx = order[(rr + attempt) % len(order)]
                if sims[idx].fits(req):
                    picked = idx
                    rr = (order.index(idx) + 1) % len(order)
                    break
        if picked is None:
            return None
        sims[picked].take(req)
        if picked not in used:
            used.append(picked)
        chosen.append(by_sim[id(sims[picked])])
    return chosen


def _take_if_fits(cand: _Candidate, req) -> bool:
    if not cand.fits(req):
        return False
    cand.take(req)
    return True


def _cluster_reserve(w, head, pg: PlacementGroup,
                     ici_slice: Optional[str] = None,
                     timeout: float = 300.0) -> None:
    from ray_tpu._private.rpc import RpcClient

    bundles = pg.bundle_specs
    milli = [to_milli(b) for b in bundles]
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        candidates = _candidates(w, head)
        # ICI-slice gang constraint: restrict to one slice's nodes.
        if ici_slice is not None:
            groups: Dict[str, List[_Candidate]] = {}
            for c in candidates:
                label = c.labels.get("ici_slice")
                if label is not None:
                    groups.setdefault(label, []).append(c)
            if ici_slice != "auto":
                groups = {ici_slice: groups.get(ici_slice, [])}
            plan = None
            for label in sorted(
                    groups, key=lambda g: -sum(sum(c.avail.values())
                                               for c in groups[g])):
                plan = _plan_bundles(groups[label], milli, pg.strategy)
                if plan is not None:
                    break
        else:
            plan = _plan_bundles(candidates, milli, pg.strategy)
        if plan is None:
            if pg.strategy in ("STRICT_PACK", "STRICT_SPREAD") and \
                    not _could_ever_fit(w, head, pg, milli, ici_slice):
                pg._failed = (
                    f"{pg.strategy} placement group cannot be satisfied "
                    f"by the current cluster")
                pg._ready.set()
                return
            time.sleep(0.2)
            continue

        # Phase 1: prepare everywhere.
        prepared: List[int] = []
        ok = True
        for i, (cand, req) in enumerate(zip(plan, milli)):
            if cand.node_id is None:
                got = w.backend.resources.try_acquire(req)
            else:
                record = head.nodes.get(cand.node_id)
                try:
                    got = record is not None and RpcClient.to(
                        record.address).call(
                        "prepare_bundle", pg_id=pg.id.binary(),
                        index=i, request=req)
                except Exception:
                    got = False
            if got:
                prepared.append(i)
            else:
                ok = False
                break
        if not ok:
            # Abort: return everything prepared, then retry.
            for i in prepared:
                cand = plan[i]
                if cand.node_id is None:
                    w.backend.resources.release(milli[i])
                else:
                    record = head.nodes.get(cand.node_id)
                    if record is not None:
                        try:
                            RpcClient.to(record.address).call(
                                "return_bundle", pg_id=pg.id.binary(),
                                index=i)
                        except Exception:
                            pass
            time.sleep(0.1)
            continue

        # Phase 2: commit. A commit failure (node died between prepare
        # and commit) aborts the whole round: tear down everything placed
        # so far — committed bundles included — and retry from scratch,
        # never recording a bundle the node doesn't actually hold.
        committed = []
        commit_ok = True
        for i, (cand, bundle) in enumerate(zip(plan, bundles)):
            if cand.node_id is None:
                w.backend.bundle_resources[(pg.id, i)] = ResourceSet(bundle)
                committed.append(i)
                continue
            record = head.nodes.get(cand.node_id)
            try:
                if record is None or not RpcClient.to(record.address).call(
                        "commit_bundle", pg_id=pg.id.binary(), index=i,
                        bundle=bundle):
                    commit_ok = False
                    break
                committed.append(i)
            except Exception:
                commit_ok = False
                break
        if not commit_ok:
            for i in range(len(plan)):
                cand = plan[i]
                if cand.node_id is None:
                    # Head-local: phase 1 acquired the resources whether
                    # or not phase 2 created the pool yet — drop the pool
                    # if present and give the resources back either way.
                    w.backend.bundle_resources.pop((pg.id, i), None)
                    w.backend.resources.release(milli[i])
                else:
                    record = head.nodes.get(cand.node_id)
                    if record is not None:
                        try:
                            RpcClient.to(record.address).call(
                                "return_bundle", pg_id=pg.id.binary(),
                                index=i)
                        except Exception:
                            pass
            time.sleep(0.2)
            continue
        for i, cand in enumerate(plan):
            head.pg_bundle_nodes[(pg.id.binary(), i)] = cand.node_id
        pg.bundle_nodes = [c.node_id for c in plan]
        # Re-persist now that bundle locations are known, so a restarted
        # head recovers the PLACED group (bundle->node map included).
        try:
            head.worker.gcs.register_placement_group(pg)
        except Exception:
            pass
        pg._ready.set()
        return
    pg._failed = "placement group reservation timed out"
    pg._ready.set()


def _could_ever_fit(w, head, pg, milli, ici_slice) -> bool:
    """Feasibility against *total* cluster capacity (ignoring current
    usage): if even empty nodes couldn't host it, fail fast."""
    from ray_tpu._private.resources import to_milli as _tm

    totals = [_Candidate(None, _tm(dict(w.backend.resources.total)), {})]
    for record in head.nodes.values():
        if record.alive:
            totals.append(_Candidate(
                record.node_id, _tm(dict(record.resources)), record.labels))
    if ici_slice is not None:
        if ici_slice == "auto":
            slices = {c.labels.get("ici_slice")
                      for c in totals} - {None}
            return any(_plan_bundles(
                [c for c in totals if c.labels.get("ici_slice") == s],
                milli, pg.strategy) is not None for s in slices)
        totals = [c for c in totals
                  if c.labels.get("ici_slice") == ici_slice]
    return _plan_bundles(totals, milli, pg.strategy) is not None


def remove_placement_group(pg: PlacementGroup) -> None:
    from ray_tpu._private.rpc import RpcClient

    w = worker_mod.global_worker()
    backend = w.backend
    # Cluster-held bundles: tell each owning node to return its bundle.
    head = getattr(w, "cluster_head", None)
    if head is not None:
        for (pgid, i), node_id in list(head.pg_bundle_nodes.items()):
            if pgid != pg.id.binary():
                continue
            head.pg_bundle_nodes.pop((pgid, i), None)
            if node_id is None:
                continue  # head-local: released via bundle_resources below
            record = head.nodes.get(node_id)
            if record is not None and record.alive:
                try:
                    RpcClient.to(record.address).call(
                        "return_bundle", pg_id=pgid, index=i)
                except Exception:
                    pass
    released: Dict[str, int] = {}
    for (gid, i) in list(backend.bundle_resources):
        if gid == pg.id:
            pool = backend.bundle_resources.pop((gid, i))
            for k, v in to_milli(pool.total).items():
                released[k] = released.get(k, 0) + v
    if released:
        backend.resources.release(released)
    w.gcs.remove_placement_group(pg.id)


def get_placement_group(name: str) -> PlacementGroup:
    w = worker_mod.global_worker()
    for pg in w.gcs.placement_group_table().values():
        if pg.name == name:
            return pg
    raise ValueError(f"placement group {name!r} not found")


def placement_group_table() -> dict:
    w = worker_mod.global_worker()
    return {
        pg.id.hex(): {
            "name": pg.name,
            "strategy": pg.strategy,
            "bundles": pg.bundle_specs,
            "state": "CREATED" if pg._ready.is_set() and not pg._failed
            else ("REMOVED" if pg._failed else "PENDING"),
        }
        for pg in w.gcs.placement_group_table().values()
    }


@dataclass
class PlacementGroupFactory:
    """Deferred PG creation spec (reference: `tune/execution/
    placement_groups.py` PlacementGroupFactory) — what ScalingConfig lowers
    to and what Tune's trial executor reserves per trial."""

    bundles: List[Dict[str, float]]
    strategy: str = "PACK"

    def __call__(self) -> PlacementGroup:
        # Bundle 0 (trainer overhead) may be empty → drop zero bundles.
        real = [b for b in self.bundles if b and any(v > 0
                                                    for v in b.values())]
        return placement_group(real or [{"CPU": 0.001}],
                               strategy=self.strategy)

    def required_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for b in self.bundles:
            for k, v in b.items():
                out[k] = out.get(k, 0) + v
        return out
