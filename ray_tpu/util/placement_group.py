"""Placement groups: gang reservation of resource bundles.

Reference: `python/ray/util/placement_group.py:33,136` (API) and the
raylet-side 2PC reservation (`raylet/placement_group_resource_manager.h`).
Strategies PACK/SPREAD/STRICT_PACK/STRICT_SPREAD keep reference semantics;
the TPU extension is an optional ``ici_slice`` bundle label so STRICT_PACK
groups can demand a contiguous ICI sub-slice (chips that neighbour on the
torus) rather than any N chips — the gang-scheduling constraint GPUs never
needed (SURVEY.md §7 "hard parts").

On the single-node in-process backend, reservation carves bundle pools out
of the node's ResourceSet atomically (all-or-nothing, the 2PC degenerate
case); the cluster backend will run prepare/commit across nodes on the
same interfaces.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from ray_tpu._private.ids import PlacementGroupID
from ray_tpu._private.resources import ResourceSet, to_milli
from ray_tpu._private.task_spec import (
    PlacementGroupSchedulingStrategy,
)
from ray_tpu._private import worker as worker_mod
from ray_tpu import exceptions as exc

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    """Handle to a reserved (or pending) group of bundles."""

    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]],
                 strategy: str, name: str = ""):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy
        self.name = name
        self._ready = threading.Event()
        self._failed: Optional[str] = None

    def ready(self):
        """Returns an ObjectRef resolving when reservation completes
        (reference returns a ref for `ray.get(pg.ready())`)."""
        import ray_tpu

        @ray_tpu.remote
        def _wait(pg_name, pg):
            pg.wait(timeout=60.0)
            return pg

        return _wait.options(num_cpus=0.001).remote(self.name, self)

    def wait(self, timeout: Optional[float] = None) -> bool:
        ok = self._ready.wait(timeout)
        if self._failed:
            raise exc.PlacementGroupSchedulingError(self._failed)
        return ok

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def __reduce__(self):
        # Handles are pass-by-reference through the object store: the
        # in-process registry resolves by id.
        return (_lookup_pg, (self.id,))


def _lookup_pg(pg_id):
    w = worker_mod.global_worker()
    table = w.gcs.placement_group_table()
    pg = table.get(pg_id)
    if pg is None:
        raise exc.PlacementGroupSchedulingError(
            f"placement group {pg_id} not found")
    return pg


def placement_group(bundles: List[Dict[str, float]], *,
                    strategy: str = "PACK", name: str = "",
                    lifetime: Optional[str] = None) -> PlacementGroup:
    """Reserve bundles. Reference: `util/placement_group.py:33`."""
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles:
        raise ValueError("placement group requires at least one bundle")
    for b in bundles:
        if not b or all(v == 0 for v in b.values()):
            raise ValueError(f"bundle must request resources: {b}")
    w = worker_mod.global_worker()
    pg = PlacementGroup(PlacementGroupID.from_random(), bundles, strategy,
                        name)
    w.gcs.register_placement_group(pg)
    backend = w.backend

    # Single-node reservation: all bundles land on this node. STRICT_SPREAD
    # demands distinct nodes, which a single-node cluster cannot satisfy
    # unless there is exactly one bundle.
    if strategy == "STRICT_SPREAD" and len(bundles) > 1 and \
            len(w.gcs.nodes()) == 1:
        pg._failed = (
            "STRICT_SPREAD with multiple bundles cannot be satisfied on a "
            "single-node cluster")
        pg._ready.set()
        return pg

    milli = [to_milli(b) for b in bundles]
    # All-or-nothing: acquire every bundle from the node pool, then carve
    # per-bundle ResourceSets (the 2PC prepare+commit collapsed to one op).
    acquired = []
    ok = True
    for req in milli:
        if backend.resources.try_acquire(req):
            acquired.append(req)
        else:
            ok = False
            break
    if not ok:
        for req in acquired:
            backend.resources.release(req)
        # Leave pending; a retry loop waits for resources to free up.
        def _retry():
            deadline = time.monotonic() + 300
            while time.monotonic() < deadline:
                got = []
                done = True
                for req in milli:
                    if backend.resources.try_acquire(req):
                        got.append(req)
                    else:
                        done = False
                        break
                if done:
                    _commit(backend, pg, bundles)
                    return
                for req in got:
                    backend.resources.release(req)
                backend.resources.wait_for_change(timeout=0.2)
            pg._failed = "placement group reservation timed out"
            pg._ready.set()

        threading.Thread(target=_retry, daemon=True).start()
        return pg

    _commit(backend, pg, bundles)
    return pg


def _commit(backend, pg: PlacementGroup, bundles):
    for i, b in enumerate(bundles):
        backend.bundle_resources[(pg.id, i)] = ResourceSet(b)
    pg._ready.set()


def remove_placement_group(pg: PlacementGroup) -> None:
    w = worker_mod.global_worker()
    backend = w.backend
    released: Dict[str, int] = {}
    for (gid, i) in list(backend.bundle_resources):
        if gid == pg.id:
            pool = backend.bundle_resources.pop((gid, i))
            for k, v in to_milli(pool.total).items():
                released[k] = released.get(k, 0) + v
    if released:
        backend.resources.release(released)
    w.gcs.remove_placement_group(pg.id)


def get_placement_group(name: str) -> PlacementGroup:
    w = worker_mod.global_worker()
    for pg in w.gcs.placement_group_table().values():
        if pg.name == name:
            return pg
    raise ValueError(f"placement group {name!r} not found")


def placement_group_table() -> dict:
    w = worker_mod.global_worker()
    return {
        pg.id.hex(): {
            "name": pg.name,
            "strategy": pg.strategy,
            "bundles": pg.bundle_specs,
            "state": "CREATED" if pg._ready.is_set() and not pg._failed
            else ("REMOVED" if pg._failed else "PENDING"),
        }
        for pg in w.gcs.placement_group_table().values()
    }


@dataclass
class PlacementGroupFactory:
    """Deferred PG creation spec (reference: `tune/execution/
    placement_groups.py` PlacementGroupFactory) — what ScalingConfig lowers
    to and what Tune's trial executor reserves per trial."""

    bundles: List[Dict[str, float]]
    strategy: str = "PACK"

    def __call__(self) -> PlacementGroup:
        # Bundle 0 (trainer overhead) may be empty → drop zero bundles.
        real = [b for b in self.bundles if b and any(v > 0
                                                    for v in b.values())]
        return placement_group(real or [{"CPU": 0.001}],
                               strategy=self.strategy)

    def required_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for b in self.bundles:
            for k, v in b.items():
                out[k] = out.get(k, 0) + v
        return out
