"""Runtime (host-level) collectives between actors/tasks.

Reference: `python/ray/util/collective/collective.py` — NCCL/Gloo process
groups with allreduce/allgather/broadcast/barrier (`:258-615`). On TPU the
*tensor* plane lives inside compiled XLA programs (`ray_tpu.parallel`);
this module is the *host* plane replacement for Gloo: CPU-side collectives
over the object plane, used for DDP-style gradient averaging between
worker actors on CPU paths, metric reduction, and rendezvous/barriers.

Implementation: a named rendezvous actor per group; ranks contribute
values per operation sequence number and block until the reduction is
complete. Collectives must be called in the same order on every rank
(the same contract NCCL imposes). Large tensors don't funnel through the
one actor: allreduce shards them across a pool of per-chunk rendezvous
actors (reduce-scatter + all-gather shape — each shard actor moves and
reduces 1/K of the bytes, in parallel), so the single-actor path is only
the small-value/control plane.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

import numpy as np

import ray_tpu


class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"
    MEAN = "mean"


@ray_tpu.remote
class _Rendezvous:
    """Holds in-flight collective rounds for one group."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._lock = threading.Condition()
        self._rounds: Dict[int, dict] = {}

    def _round(self, seq: int) -> dict:
        if seq not in self._rounds:
            self._rounds[seq] = {"values": {}, "result": None, "reads": 0}
        return self._rounds[seq]

    def contribute(self, seq: int, rank: int, value, op: str,
                   root: Optional[int] = None, timeout: float = 60.0):
        with self._lock:
            r = self._round(seq)
            r["values"][rank] = value
            if len(r["values"]) == self.world_size:
                r["result"] = _reduce_values(r["values"], op, root)
                self._lock.notify_all()
            else:
                ok = self._lock.wait_for(
                    lambda: r["result"] is not None, timeout=timeout)
                if not ok:
                    raise TimeoutError(
                        f"collective round {seq}: only "
                        f"{len(r['values'])}/{self.world_size} ranks arrived")
            result = r["result"]
            r["reads"] += 1
            if r["reads"] == self.world_size:
                del self._rounds[seq]
            return result

    # -- point-to-point (send/recv) --------------------------------------
    # Reference `util/collective/collective.py:541-615`: only the two
    # endpoint ranks participate, so p2p traffic rides its own mailbox
    # keyed by (src, dst, per-pair seq) — it never perturbs the
    # group-wide round sequencing.

    def p2p_put(self, key, value, timeout: float = 60.0):
        with self._lock:
            self._p2p().setdefault(key, {})["value"] = value
            self._lock.notify_all()
            ok = self._lock.wait_for(
                lambda: self._p2p().get(key, {}).get("taken"),
                timeout=timeout)
            if not ok:
                self._p2p().pop(key, None)
                raise TimeoutError(f"send {key}: receiver never arrived")
            self._p2p().pop(key, None)
            return True

    def p2p_get(self, key, timeout: float = 60.0):
        with self._lock:
            ok = self._lock.wait_for(
                lambda: "value" in self._p2p().get(key, {}),
                timeout=timeout)
            if not ok:
                raise TimeoutError(f"recv {key}: sender never arrived")
            slot = self._p2p()[key]
            slot["taken"] = True
            self._lock.notify_all()
            return slot["value"]

    def _p2p(self) -> dict:
        if not hasattr(self, "_p2p_slots"):
            self._p2p_slots = {}
        return self._p2p_slots





def _reduce_values(values: Dict[int, Any], op: str, root: Optional[int]):
    if op == "gather":
        return [values[r] for r in sorted(values)]
    if op == "broadcast":
        return values[root]
    first = values[min(values)]
    if isinstance(first, list):
        # Pytree-leaf lists: reduce position-wise in one round.
        per_rank = [values[r] for r in sorted(values)]
        return [
            _reduce_values(
                {r: per_rank[r][i] for r in range(len(per_rank))}, op, root)
            for i in range(len(first))
        ]
    arrs = [np.asarray(values[r]) for r in sorted(values)]
    if op == ReduceOp.SUM:
        return sum(arrs)
    if op == ReduceOp.PRODUCT:
        out = arrs[0].copy()
        for a in arrs[1:]:
            out = out * a
        return out
    if op == ReduceOp.MIN:
        return np.minimum.reduce(arrs)
    if op == ReduceOp.MAX:
        return np.maximum.reduce(arrs)
    if op == ReduceOp.MEAN:
        return sum(arrs) / len(arrs)
    if op == "barrier":
        return 0
    raise ValueError(f"unknown op {op}")


# Tensors above this size shard across the actor pool instead of moving
# whole through one rendezvous actor.
_SHARD_THRESHOLD_BYTES = 256 * 1024
_SHARD_ACTORS = 4


class _GroupState:
    def __init__(self, name: str, world_size: int, rank: int, actor,
                 shard_actors=None):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.actor = actor
        self.shard_actors = shard_actors or []
        self.seq = 0
        # Per-peer p2p sequence counters, independent per direction:
        # sends to (and recvs from) each peer match up in program order
        # without touching the group-wide collective sequencing.
        self.p2p_seq: Dict[Any, int] = {}

    def next_seq(self) -> int:
        s = self.seq
        self.seq += 1
        return s

    def next_p2p_seq(self, src: int, dst: int) -> int:
        key = (src, dst)
        s = self.p2p_seq.get(key, 0)
        self.p2p_seq[key] = s + 1
        return s


_local = threading.local()
# Actor-keyed group registries: a pooled actor's method calls run on
# whatever executor thread serves the activation (multi-slot since the
# serve scale-out PR), so per-THREAD state would vanish between an
# actor's __init__ and its next call. The registry is therefore keyed
# by the executing ACTOR when there is one (read from the ambient task
# context) and falls back to the thread for driver/plain-task code —
# which preserves the original semantics exactly where threads ARE the
# identity. ``destroy_collective_group`` shrinks it (reset-capable).
_ACTOR_GROUPS: Dict[bytes, Dict[str, "_GroupState"]] = {}
_ACTOR_GROUPS_LOCK = threading.Lock()
_death_hook_installed = False


def _on_actor_dead(actor_id) -> None:
    """Backend death hook: a dying actor's group registry dies with it
    — without this, actor churn leaks one row (holding _GroupState +
    rendezvous handles) per collective-using actor for the process
    lifetime."""
    with _ACTOR_GROUPS_LOCK:
        _ACTOR_GROUPS.pop(actor_id.binary(), None)


def _ensure_death_hook() -> None:
    global _death_hook_installed
    if _death_hook_installed:
        return
    with _ACTOR_GROUPS_LOCK:
        if _death_hook_installed:
            return
        _death_hook_installed = True
    from ray_tpu._private.local_backend import register_actor_death_hook

    register_actor_death_hook(_on_actor_dead)


def _groups() -> Dict[str, "_GroupState"]:
    try:
        from ray_tpu._private.worker import global_worker_or_none

        w = global_worker_or_none()
        if w is not None:
            ctx = w.task_context.current()
            if ctx is not None:
                spec = ctx.get("task_spec")
                aid = getattr(spec, "actor_id", None)
                if aid is not None:
                    key = aid.binary()
                    with _ACTOR_GROUPS_LOCK:
                        groups = _ACTOR_GROUPS.get(key)
                        if groups is None:
                            groups = _ACTOR_GROUPS[key] = {}
                    return groups
    except Exception:
        pass
    if not hasattr(_local, "groups"):
        _local.groups = {}
    return _local.groups


def init_collective_group(world_size: int, rank: int,
                          backend: str = "object_store",
                          group_name: str = "default") -> None:
    """Reference: `util/collective/collective.py:258` (init_collective_group).
    `backend` accepted for API parity; the object-plane rendezvous is the
    only host backend."""
    def get_or_create(name):
        try:
            return ray_tpu.get_actor(name)
        except ValueError:
            try:
                return _Rendezvous.options(
                    name=name, max_concurrency=max(64, world_size * 4),
                    lifetime="detached").remote(world_size)
            except ValueError:
                return ray_tpu.get_actor(name)

    _ensure_death_hook()
    actor = get_or_create(f"__collective::{group_name}")
    shards = [get_or_create(f"__collective::{group_name}::shard{j}")
              for j in range(_SHARD_ACTORS)]
    _groups()[group_name] = _GroupState(group_name, world_size, rank,
                                        actor, shards)


def set_default_group(group_name: str) -> None:
    """Alias an initialized group as ``"default"`` so user code can call
    the collective ops without naming a group (the train-loop wrapper's
    contract). Public: reaching into the registry from other packages
    is a layering violation (raylint R3)."""
    _groups()["default"] = _groups()[group_name]


def clear_default_group() -> None:
    _groups().pop("default", None)


def destroy_collective_group(group_name: str = "default") -> None:
    groups = _groups()
    st = groups.pop(group_name, None)
    if not groups:
        # Last group of this actor's registry: drop the actor-keyed
        # row too, so dead actors don't accumulate empty dicts.
        with _ACTOR_GROUPS_LOCK:
            for key, val in list(_ACTOR_GROUPS.items()):
                if val is groups:
                    del _ACTOR_GROUPS[key]
                    break
    if st is not None:
        for a in [st.actor] + list(st.shard_actors):
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups()


def get_rank(group_name: str = "default") -> int:
    return _groups()[group_name].rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _groups()[group_name].world_size


def _call(group_name: str, value, op: str, root: Optional[int] = None):
    st = _groups().get(group_name)
    if st is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized on this "
            "worker; call init_collective_group first")
    seq = st.next_seq()
    return ray_tpu.get(st.actor.contribute.remote(seq, st.rank, value, op,
                                                  root))


def allreduce(tensor, group_name: str = "default",
              op: str = ReduceOp.SUM):
    arr = np.asarray(tensor)
    st = _groups().get(group_name)
    if (st is None or not st.shard_actors
            or arr.nbytes < _SHARD_THRESHOLD_BYTES
            or op in ("gather", "broadcast", "barrier")):
        return _call(group_name, arr, op)
    # Sharded path: chunk j of every rank's flat tensor meets at shard
    # actor j (reduce-scatter), each rank reads back all reduced chunks
    # (all-gather). One seq per collective, shared by all chunks.
    seq = st.next_seq()
    flat = arr.reshape(-1)
    chunks = np.array_split(flat, len(st.shard_actors))
    refs = [a.contribute.remote(seq, st.rank, c, op)
            for a, c in zip(st.shard_actors, chunks)]
    reduced = ray_tpu.get(refs)
    return np.concatenate(reduced).reshape(arr.shape)


def allgather(tensor, group_name: str = "default") -> list:
    return _call(group_name, np.asarray(tensor), "gather")


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _call(group_name, np.asarray(tensor), "broadcast", root=src_rank)


def reducescatter(tensor, group_name: str = "default",
                  op: str = ReduceOp.SUM):
    full = _call(group_name, np.asarray(tensor), op)
    st = _groups()[group_name]
    return np.array_split(full, st.world_size)[st.rank]

def barrier(group_name: str = "default") -> None:
    _call(group_name, 0, "barrier")


def send(tensor, dst_rank: int, group_name: str = "default",
         timeout: float = 60.0) -> None:
    """Point-to-point send to ``dst_rank`` (reference:
    `util/collective/collective.py:541` `send`). Blocks until the
    matching :func:`recv` takes the value — NCCL-like rendezvous
    semantics, so a send with no receiver surfaces as a timeout rather
    than silently buffering."""
    st = _groups().get(group_name)
    if st is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized on this "
            "worker; call init_collective_group first")
    if dst_rank == st.rank:
        raise ValueError("cannot send to self")
    if not 0 <= dst_rank < st.world_size:
        raise ValueError(f"dst_rank {dst_rank} out of range "
                         f"[0, {st.world_size})")
    seq = st.next_p2p_seq(st.rank, dst_rank)
    try:
        ray_tpu.get(st.actor.p2p_put.remote(
            (st.rank, dst_rank, seq), np.asarray(tensor), timeout))
    except BaseException:
        # Roll back so a timed-out send can be retried without
        # permanently desyncing the pair's sequence numbers.
        st.p2p_seq[(st.rank, dst_rank)] -= 1
        raise


def recv(tensor, src_rank: int, group_name: str = "default",
         timeout: float = 60.0):
    """Point-to-point receive from ``src_rank`` (reference:
    `util/collective/collective.py:590` `recv`): fills ``tensor``
    in place when it's a writable ndarray of matching shape (the
    reference's contract) and also returns the received array."""
    st = _groups().get(group_name)
    if st is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized on this "
            "worker; call init_collective_group first")
    if src_rank == st.rank:
        raise ValueError("cannot recv from self")
    if not 0 <= src_rank < st.world_size:
        raise ValueError(f"src_rank {src_rank} out of range "
                         f"[0, {st.world_size})")
    seq = st.next_p2p_seq(src_rank, st.rank)
    try:
        value = np.asarray(ray_tpu.get(st.actor.p2p_get.remote(
            (src_rank, st.rank, seq), timeout)))
    except BaseException:
        st.p2p_seq[(src_rank, st.rank)] -= 1
        raise
    if isinstance(tensor, np.ndarray) and tensor.shape == value.shape \
            and tensor.flags.writeable:
        np.copyto(tensor, value)
    return value


def allreduce_pytree(tree, group_name: str = "default",
                     op: str = ReduceOp.MEAN):
    """Convenience for gradient averaging. Small leaves batch into one
    rendezvous round; large leaves take the sharded allreduce path (the
    deterministic size split keeps sequence numbers aligned across
    ranks)."""
    import jax

    leaves, treedef = jax.tree.flatten(tree)
    host = [np.asarray(x) for x in leaves]
    small_idx = [i for i, a in enumerate(host)
                 if a.nbytes < _SHARD_THRESHOLD_BYTES]
    large_idx = [i for i, a in enumerate(host)
                 if a.nbytes >= _SHARD_THRESHOLD_BYTES]
    out: list = [None] * len(host)
    if small_idx:
        reduced = _call(group_name, [host[i] for i in small_idx], op)
        for i, r in zip(small_idx, reduced):
            out[i] = r
    for i in large_idx:
        out[i] = allreduce(host[i], group_name, op)
    return jax.tree.unflatten(treedef, out)
