"""Actor-backed distributed Queue (reference `python/ray/util/queue.py`)."""

from __future__ import annotations

import queue as _pyqueue
import threading
from typing import Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


@ray_tpu.remote
class _QueueActor:
    def __init__(self, maxsize: int):
        self.q = _pyqueue.Queue(maxsize=maxsize)

    def put(self, item, timeout: Optional[float] = None) -> bool:
        try:
            self.q.put(item, timeout=timeout, block=timeout is not None)
            return True
        except _pyqueue.Full:
            return False

    def put_nowait(self, item) -> bool:
        try:
            self.q.put_nowait(item)
            return True
        except _pyqueue.Full:
            return False

    def get(self, timeout: Optional[float] = None):
        try:
            return True, self.q.get(timeout=timeout,
                                    block=timeout is not None)
        except _pyqueue.Empty:
            return False, None

    def get_nowait(self):
        try:
            return True, self.q.get_nowait()
        except _pyqueue.Empty:
            return False, None

    def qsize(self) -> int:
        return self.q.qsize()

    def empty(self) -> bool:
        return self.q.empty()

    def full(self) -> bool:
        return self.q.full()


class Queue:
    def __init__(self, maxsize: int = 0, *, actor_options: Optional[dict] = None):
        opts = dict(actor_options or {})
        opts.setdefault("max_concurrency", 16)
        self.actor = _QueueActor.options(**opts).remote(maxsize)

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if not block:
            ok = ray_tpu.get(self.actor.put_nowait.remote(item))
        else:
            ok = ray_tpu.get(self.actor.put.remote(item, timeout or 1e9))
        if not ok:
            raise Full()

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True, timeout: Optional[float] = None):
        if not block:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
        else:
            ok, item = ray_tpu.get(self.actor.get.remote(timeout or 1e9))
        if not ok:
            raise Empty()
        return item

    def get_nowait(self):
        return self.get(block=False)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    def put_async(self, item):
        return self.actor.put.remote(item, 1e9)

    def get_async(self, timeout: Optional[float] = None):
        """ObjectRef resolving to ``(ok, item)`` — awaitable from
        asyncio code (``ok`` False on timeout). The event-loop
        counterpart of :meth:`get` for consumers that must not block
        their loop (the HTTP proxy's SSE stream pump)."""
        return self.actor.get.remote(timeout or 1e9)

    def shutdown(self, block: bool = True):
        """Kill the backing actor. ``block=False`` hands the kill (a
        synchronous control-plane RPC in cluster mode) to a daemon
        thread — the variant event-loop consumers must use, since the
        blocking form would stall every coroutine on their loop."""
        if block:
            ray_tpu.kill(self.actor)
            return
        threading.Thread(target=self._kill_quietly, daemon=True,
                         name="queue-shutdown").start()

    def _kill_quietly(self):
        try:
            ray_tpu.kill(self.actor)
        except Exception:
            pass  # actor already dead / session torn down
