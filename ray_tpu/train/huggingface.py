"""HuggingFace Transformers trainer integration.

Reference: `python/ray/train/huggingface/huggingface_trainer.py` — run a
user-built `transformers.Trainer` inside Train workers, with the
framework owning placement, dataset feeding, metric reporting and
checkpointing. Same contract here: the user's ``trainer_init_per_worker
(train_dataset, eval_dataset, **config) -> transformers.Trainer`` runs
in each Train worker (torch CPU in this image; the TPU story for LLMs is
the native JAX stack — `models/hf.py` converts HF checkpoints INTO it);
a callback bridges HF's log/save events to `session.report`.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.air import session
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer


class HuggingFaceTrainer(DataParallelTrainer):
    def __init__(self, trainer_init_per_worker: Callable, *,
                 trainer_init_config: Optional[Dict[str, Any]] = None,
                 **kwargs):
        sc = kwargs.get("scaling_config")
        if sc is not None and getattr(sc, "num_workers", 1) not in (None,
                                                                    1):
            raise ValueError(
                "HuggingFaceTrainer runs the HF Trainer in ONE worker "
                "(no cross-worker gradient sync is wired for torch "
                "here); num_workers>1 would train N independent models "
                "on 1/N shards each — set num_workers=1.")
        init_fn = trainer_init_per_worker
        init_cfg = dict(trainer_init_config or {})

        def train_loop(config):
            import torch  # noqa: F401 — surface a clear error early

            from transformers.trainer_callback import TrainerCallback

            class _ReportCallback(TrainerCallback):
                def on_log(self, args, state, control, logs=None,
                           **kw):
                    if logs:
                        metrics = {k: v for k, v in logs.items()
                                   if isinstance(v, (int, float))}
                        metrics["step"] = state.global_step
                        session.report(metrics)

            train_ds = session.get_dataset_shard("train")
            eval_ds = session.get_dataset_shard("evaluation")
            hf_trainer = init_fn(train_ds, eval_ds, **init_cfg)
            hf_trainer.add_callback(_ReportCallback())
            result = hf_trainer.train()
            final = {k: v for k, v in (result.metrics or {}).items()
                     if isinstance(v, (int, float))}
            # Ship the fitted weights as the terminal checkpoint.
            state_dict = {
                k: v.detach().cpu().numpy()
                for k, v in hf_trainer.model.state_dict().items()
            }
            session.report(final or {"done": 1},
                           checkpoint=Checkpoint.from_dict(
                               {"state_dict": state_dict}))

        super().__init__(train_loop, **kwargs)

    @staticmethod
    def get_state_dict(checkpoint: Checkpoint) -> Dict[str, Any]:
        return checkpoint.to_dict()["state_dict"]
