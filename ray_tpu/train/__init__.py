"""ray_tpu.train: distributed training (reference `python/ray/train/`).

`JaxTrainer` replaces TorchTrainer: SPMD mesh programs instead of NCCL
process groups. `DataParallelTrainer` is the generic worker-group driver;
`BackendExecutor`/`WorkerGroup` are the internals (SURVEY.md §3.3 call
stack).
"""

from ray_tpu.train.backend import Backend, BackendConfig  # noqa: F401
from ray_tpu.train.base_trainer import BaseTrainer  # noqa: F401
from ray_tpu.train.data_parallel_trainer import (  # noqa: F401
    DataParallelTrainer,
)
from ray_tpu.train.jax_trainer import (  # noqa: F401
    JaxConfig,
    JaxTrainer,
    allreduce_gradients,
    prepare_mesh,
)
from ray_tpu.train.gbdt_trainer import (  # noqa: F401
    GBDTTrainer,
    LightGBMTrainer,
    XGBoostTrainer,
)
from ray_tpu.train.huggingface import HuggingFaceTrainer  # noqa: F401
from ray_tpu.train.torch import (  # noqa: F401
    TorchCheckpoint,
    TorchConfig,
    TorchTrainer,
)
from ray_tpu.train._internal.backend_executor import (  # noqa: F401
    BackendExecutor,
    TrainingFailedError,
)
from ray_tpu.train._internal.worker_group import WorkerGroup  # noqa: F401
