"""WorkerGroup: the actor fleet that runs a train loop.

Reference: `python/ray/train/_internal/worker_group.py:92`. Each worker is
an actor; `start_training` launches the user loop on a thread inside the
actor (so the actor stays responsive to result polling — the reference
uses a `_TrainSession` thread + queue, `train/_internal/session.py:63`).
"""

from __future__ import annotations

import threading
import traceback
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air import session as session_mod


@ray_tpu.remote
class TrainWorker:
    def __init__(self):
        self._session: Optional[session_mod.TrainSession] = None
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[str] = None
        self._error_obj: Optional[BaseException] = None
        self._done = threading.Event()
        self._env: Dict[str, str] = {}

    def set_env(self, env: Dict[str, str]):
        import os

        self._env = env
        os.environ.update(env)
        return True

    def start_training(self, train_fn: Callable, config: Dict[str, Any],
                       session_kwargs: Dict[str, Any]) -> bool:
        self._session = session_mod.TrainSession(**session_kwargs)
        self._done.clear()
        self._error = None

        def run():
            session_mod.set_session(self._session)
            try:
                if config is not None:
                    train_fn(config)
                else:
                    train_fn()
            except BaseException as e:  # noqa: BLE001 - reported to driver
                self._error = traceback.format_exc()
                self._error_obj = e
            finally:
                session_mod.set_session(None)
                self._done.set()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="train-loop")
        self._thread.start()
        return True

    def poll(self) -> Dict[str, Any]:
        """Drain new results; report liveness + error state."""
        results = self._session.drain_results() if self._session else []
        return {
            "results": results,
            "done": self._done.is_set(),
            "error": self._error,
        }

    def join(self, timeout: Optional[float] = None) -> bool:
        self._done.wait(timeout)
        if self._error:
            raise RuntimeError(f"train loop failed:\n{self._error}")
        return True

    def execute(self, fn: Callable, *args, **kwargs):
        """Run an arbitrary function on the worker (reference
        WorkerGroup.execute)."""
        return fn(*args, **kwargs)

    def shutdown(self) -> bool:
        return True


class WorkerGroup:
    def __init__(self, num_workers: int,
                 resources_per_worker: Optional[Dict[str, float]] = None,
                 placement_group=None,
                 isolate_process: bool = False):
        from ray_tpu.util.scheduling_strategies import (
            PlacementGroupSchedulingStrategy,
        )

        res = dict(resources_per_worker or {"CPU": 1})
        opts: Dict[str, Any] = {
            "num_cpus": res.pop("CPU", 1),
        }
        if isolate_process:
            # Each worker in its own OS process: required for
            # jax.distributed (one JAX process per rank). Pass through
            # as-is ("spawn" or True).
            opts["isolate_process"] = isolate_process
        if "TPU" in res:
            opts["num_tpus"] = res.pop("TPU")
        if res:
            opts["resources"] = res
        self.workers: List[Any] = []
        for i in range(num_workers):
            o = dict(opts)
            if placement_group is not None:
                o["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                    placement_group=placement_group,
                    # bundle 0 is the trainer's; workers take 1..N
                    placement_group_bundle_index=i + 1
                    if placement_group.bundle_count > num_workers else i,
                )
            self.workers.append(TrainWorker.options(**o).remote())

    def __len__(self):
        return len(self.workers)

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return ray_tpu.get([w.execute.remote(fn, *args, **kwargs)
                            for w in self.workers])

    def execute_single(self, idx: int, fn: Callable, *args, **kwargs):
        return ray_tpu.get(self.workers[idx].execute.remote(fn, *args,
                                                            **kwargs))

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
