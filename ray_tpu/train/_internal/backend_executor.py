"""BackendExecutor: orchestrates a WorkerGroup through a training run.

Reference: `python/ray/train/_internal/backend_executor.py:43` — `start`
creates the worker group in the run's placement group and assigns ranks;
`start_training` launches the loop on every worker; `poll` streams
per-iteration results back (the reference's queue plumbing,
`train/_internal/session.py:322`).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train._internal.worker_group import WorkerGroup


class TrainingFailedError(RuntimeError):
    pass


class BackendExecutor:
    def __init__(self, backend_config: Optional[BackendConfig],
                 scaling_config: ScalingConfig):
        self.backend_config = backend_config or BackendConfig()
        self.backend: Backend = self.backend_config.backend_cls()()
        self.scaling_config = scaling_config
        self.worker_group: Optional[WorkerGroup] = None
        self.placement_group = None
        self._own_pg = False

    def start(self, placement_group=None):
        sc = self.scaling_config
        if placement_group is None and (sc.num_tpus_per_worker or
                                        sc.num_workers > 1):
            factory = sc.as_placement_group_factory()
            placement_group = factory()
            placement_group.wait(timeout=60)
            self._own_pg = True
        self.placement_group = placement_group
        self.worker_group = WorkerGroup(
            sc.num_workers,
            resources_per_worker=sc.worker_resources(),
            placement_group=placement_group,
            # jax.distributed needs one *fresh* OS process per rank
            # (forked children inherit unusable XLA runtime state).
            isolate_process="spawn" if getattr(
                self.backend_config, "distributed", False) else False,
        )
        self.backend.on_start(self.worker_group, self.backend_config)

    def start_training(self, train_fn: Callable,
                       config: Optional[Dict[str, Any]],
                       datasets: Optional[Dict[str, Any]] = None,
                       checkpoint: Optional[Checkpoint] = None,
                       group_name: str = "train") -> None:
        assert self.worker_group is not None, "call start() first"
        n = len(self.worker_group)
        self.backend.on_training_start(self.worker_group,
                                       self.backend_config)

        # Shard datasets across workers (reference: dataset splitting in
        # `data_parallel_trainer.py`). The "train" dataset is split; other
        # datasets are passed whole to every worker.
        shards_per_worker: List[Dict[str, Any]] = [dict() for _ in range(n)]
        for name, ds in (datasets or {}).items():
            if name == "train" and n > 1:
                for i, shard in enumerate(ds.split(n, equal=True)):
                    shards_per_worker[i][name] = shard
            else:
                for i in range(n):
                    shards_per_worker[i][name] = ds

        unique = f"{group_name}-{int(time.time() * 1e6) & 0xFFFFFF:x}"
        calls = []
        for rank, worker in enumerate(self.worker_group.workers):
            session_kwargs = dict(
                world_rank=rank, world_size=n, local_rank=rank,
                local_world_size=n, node_rank=0,
                dataset_shards=shards_per_worker[rank],
                checkpoint=checkpoint,
            )
            wrapped = _wrap_with_collective(train_fn, n, rank, unique)
            calls.append(worker.start_training.remote(
                wrapped, config, session_kwargs))
        ray_tpu.get(calls)

    def poll(self) -> Dict[str, Any]:
        """One polling sweep over all workers. Returns
        {"results": [per-worker lists], "done": bool, "errors": [...]}"""
        polls = ray_tpu.get([w.poll.remote()
                             for w in self.worker_group.workers])
        return {
            "results": [p["results"] for p in polls],
            "done": all(p["done"] for p in polls),
            "errors": [p["error"] for p in polls],
        }

    def join(self, timeout: Optional[float] = None):
        ray_tpu.get([w.join.remote(timeout)
                     for w in self.worker_group.workers])

    def shutdown(self):
        if self.worker_group is not None:
            self.backend.on_shutdown(self.worker_group, self.backend_config)
            self.worker_group.shutdown()
            self.worker_group = None
        if self._own_pg and self.placement_group is not None:
            from ray_tpu.util.placement_group import remove_placement_group

            try:
                remove_placement_group(self.placement_group)
            except Exception:
                pass
            self.placement_group = None


def _wrap_with_collective(train_fn: Callable, world_size: int, rank: int,
                          group_name: str) -> Callable:
    """Bind a host-collective group inside the train-loop thread, so user
    code can `ray_tpu.util.collective.allreduce(...)` out of the box."""

    def wrapped(config=None):
        from ray_tpu.util import collective

        collective.init_collective_group(world_size, rank,
                                         group_name=group_name)
        # The default group alias lets user code omit the group name.
        collective.set_default_group(group_name)
        try:
            if config is not None:
                return train_fn(config)
            return train_fn()
        finally:
            collective.clear_default_group()

    return wrapped
