"""Backend ABC: per-framework worker-group setup hooks.

Reference: `python/ray/train/backend.py` (Backend/BackendConfig) — torch's
impl sets up the NCCL process group (`train/torch/config.py:113`). TPU
backends instead wire host-level collective groups and/or
`jax.distributed` multi-host init; in-program parallelism needs no setup
(the mesh is formed inside the train loop).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class BackendConfig:
    def backend_cls(self):
        return Backend


class Backend:
    def on_start(self, worker_group, backend_config: BackendConfig):
        """Called after workers start, before the train fn runs."""

    def on_training_start(self, worker_group,
                          backend_config: BackendConfig):
        """Called right before start_training on each worker."""

    def on_shutdown(self, worker_group, backend_config: BackendConfig):
        """Called at teardown."""


@dataclass
class CollectiveGroupConfig(BackendConfig):
    """Gives every train loop a host-level object-plane collective group
    (`gloo` replacement). Group init happens inside the train-loop thread
    (the BackendExecutor wraps the user fn) because group membership is
    thread-scoped in the in-process runtime."""

    group_name: str = "train_default"

    def backend_cls(self):
        return Backend
