"""DataParallelTrainer: run a user train loop on N workers.

Reference: `python/ray/train/data_parallel_trainer.py:385`
(`training_loop` drives `BackendExecutor`). The training_loop here polls
workers and re-reports rank-0's metrics (with checkpoints) up through
`session.report`, so the same code path serves direct `.fit()` and Tune
trials.
"""

from __future__ import annotations

import inspect
import time
from typing import Any, Callable, Dict, Optional

from ray_tpu.air import session
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train.base_trainer import BaseTrainer
from ray_tpu.train._internal.backend_executor import BackendExecutor


class DataParallelTrainer(BaseTrainer):
    _backend_config_cls = BackendConfig

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 backend_config: Optional[BackendConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 preprocessor=None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config, datasets=datasets,
                         preprocessor=preprocessor,
                         resume_from_checkpoint=resume_from_checkpoint)
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.backend_config = backend_config or self._backend_config_cls()

    def training_loop(self) -> None:
        self.preprocess_datasets()
        executor = BackendExecutor(self.backend_config, self.scaling_config)
        executor.start()
        try:
            fn = self.train_loop_per_worker
            takes_config = len(
                inspect.signature(fn).parameters) >= 1
            config = self.train_loop_config if takes_config else None
            executor.start_training(
                fn if takes_config else (lambda _cfg=None: fn()),
                config=config if takes_config else {},
                datasets=self.datasets,
                checkpoint=self.resume_from_checkpoint,
            )
            while True:
                poll = executor.poll()
                errors = [e for e in poll["errors"] if e]
                # Stream rank-0 results upward, attaching checkpoints.
                rank0 = poll["results"][0]
                for metrics, ckpt in rank0:
                    session.report(metrics, checkpoint=ckpt)
                if errors:
                    raise RuntimeError(
                        "training failed on "
                        f"{len(errors)}/{len(poll['errors'])} workers:\n"
                        + errors[0])
                if poll["done"]:
                    break
                time.sleep(0.02)
        finally:
            executor.shutdown()
