"""JaxTrainer: SPMD training over TPU meshes.

The reference's TorchTrainer forms an NCCL process group per worker
(`train/torch/config.py:113`). The TPU-native model is different
(SURVEY.md §7 "multi-controller JAX"): one worker per *host*, each running
the same jit-compiled SPMD program; in-host (and cross-host, on pods)
parallelism is the `jax.sharding.Mesh`, with collectives inserted by XLA.
The trainer's job is (a) reserving the gang via placement group, (b)
initializing `jax.distributed` on each worker for multi-host, (c) handing
the train loop a ready mesh via `prepare_mesh()`.

Host-level data parallelism across *separate* processes without shared
ICI (e.g. CPU fleets) instead uses the object-plane collective group
(`ray_tpu.util.collective`) for gradient averaging — the gloo-DDP
equivalent; see `prepare_ddp`/`allreduce_gradients`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from ray_tpu.air import session
from ray_tpu.air.config import ScalingConfig
from ray_tpu.parallel.mesh import MeshConfig, create_mesh
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer


@dataclass
class JaxConfig(BackendConfig):
    """Multi-host wiring config. With `distributed=True` each worker runs
    in its own OS process (the WorkerGroup forces `isolate_process`) and
    calls `jax.distributed.initialize(coordinator, num_processes,
    process_id)` before the loop — one JAX process per host, the
    multi-controller model. Single-host runs skip it.

    ``platform`` / ``num_local_devices`` pin the per-process backend
    (e.g. platform="cpu", num_local_devices=2 gives a 2-process ×
    2-device CPU test mesh — how multi-host is exercised without a pod;
    CPU collectives ride the gloo plugin)."""

    distributed: bool = False
    coordinator_port: int = 7010
    platform: Optional[str] = None
    num_local_devices: Optional[int] = None

    def backend_cls(self):
        return JaxBackend


class JaxBackend(Backend):
    def on_training_start(self, worker_group, backend_config: JaxConfig):
        if not getattr(backend_config, "distributed", False):
            return
        import ray_tpu

        # Rank-0's node is the coordinator.
        def get_ip():
            import socket

            return socket.gethostbyname(socket.gethostname())

        ip = worker_group.execute_single(0, get_ip)
        coord = f"{ip}:{backend_config.coordinator_port}"
        n = len(worker_group)
        platform = backend_config.platform
        local = backend_config.num_local_devices

        ray_tpu.get([
            w.execute.remote(_jax_dist_init, coord, n, i, platform, local)
            for i, w in enumerate(worker_group.workers)
        ])


def _jax_dist_init(coord, n, rank, platform=None, num_local_devices=None):
    """Per-rank jax.distributed bring-up. Runs inside an isolated worker
    process; if that process was forked from a parent that already
    initialized JAX, the inherited backends are discarded first so the
    distributed client is wired into fresh ones."""
    import os
    import re

    import jax

    import jax._src.xla_bridge as xla_bridge

    if xla_bridge._backends:  # pragma: no cover - forked-worker fallback
        xla_bridge._clear_backends()
    if platform is not None:
        jax.config.update("jax_platforms", platform)
    if num_local_devices is not None and (platform or "") == "cpu":
        # Inherited test env may force a host device count; the explicit
        # per-rank setting wins.
        flags = os.environ.get("XLA_FLAGS", "")
        stripped = re.sub(
            r"--xla_force_host_platform_device_count=\d+", "", flags).strip()
        if stripped != flags:
            os.environ["XLA_FLAGS"] = stripped
        jax.config.update("jax_num_cpu_devices", num_local_devices)
    if (platform or "") == "cpu":
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(coordinator_address=coord, num_processes=n,
                               process_id=rank)
    return True


class JaxTrainer(DataParallelTrainer):
    _backend_config_cls = JaxConfig

    def __init__(self, train_loop_per_worker: Callable, *,
                 jax_config: Optional[JaxConfig] = None,
                 **kwargs):
        super().__init__(train_loop_per_worker,
                         backend_config=jax_config, **kwargs)


# -- in-loop helpers (reference parity: train.torch.prepare_model etc.) ----


def prepare_mesh(scaling_config: Optional[ScalingConfig] = None,
                 mesh_config: Optional[MeshConfig] = None):
    """Build the mesh for this worker's visible devices. Inside a Train
    worker the ScalingConfig's mesh axes apply; standalone it defaults to
    all devices on the data axis."""
    cfg = mesh_config or (scaling_config.mesh_config() if scaling_config
                          else MeshConfig())
    return create_mesh(cfg)


def allreduce_gradients(grads, group_name: str = "default"):
    """Host-plane gradient mean across the worker group (gloo-DDP
    equivalent for CPU fleets; on one mesh this is unnecessary — XLA
    averages via the batch sharding)."""
    from ray_tpu.util import collective

    if session.get_session() is None or session.get_world_size() == 1:
        return grads
    return collective.allreduce_pytree(grads, group_name=group_name,
                                       op=collective.ReduceOp.MEAN)
