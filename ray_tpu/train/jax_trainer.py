"""JaxTrainer: SPMD training over TPU meshes.

The reference's TorchTrainer forms an NCCL process group per worker
(`train/torch/config.py:113`). The TPU-native model is different
(SURVEY.md §7 "multi-controller JAX"): one worker per *host*, each running
the same jit-compiled SPMD program; in-host (and cross-host, on pods)
parallelism is the `jax.sharding.Mesh`, with collectives inserted by XLA.
The trainer's job is (a) reserving the gang via placement group, (b)
initializing `jax.distributed` on each worker for multi-host, (c) handing
the train loop a ready mesh via `prepare_mesh()`.

Host-level data parallelism across *separate* processes without shared
ICI (e.g. CPU fleets) instead uses the object-plane collective group
(`ray_tpu.util.collective`) for gradient averaging — the gloo-DDP
equivalent; see `prepare_ddp`/`allreduce_gradients`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from ray_tpu.air import session
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.parallel.mesh import MeshConfig, create_mesh
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer


@dataclass
class JaxConfig(BackendConfig):
    """Multi-host wiring config. With `distributed=True` each worker calls
    `jax.distributed.initialize(coordinator, num_processes, process_id)`
    before the loop (TPU pod / multi-process CPU); single-host runs skip
    it."""

    distributed: bool = False
    coordinator_port: int = 7010

    def backend_cls(self):
        return JaxBackend


class JaxBackend(Backend):
    def on_training_start(self, worker_group, backend_config: JaxConfig):
        if not getattr(backend_config, "distributed", False):
            return
        import ray_tpu

        # Rank-0's node is the coordinator.
        def get_ip():
            import socket

            return socket.gethostbyname(socket.gethostname())

        ip = worker_group.execute_single(0, get_ip)
        coord = f"{ip}:{backend_config.coordinator_port}"
        n = len(worker_group)

        def init_dist(coord=coord, n=n):
            def _do(rank):
                import jax

                jax.distributed.initialize(coordinator_address=coord,
                                           num_processes=n,
                                           process_id=rank)
                return True
            return _do

        ray_tpu.get([
            w.execute.remote(_jax_dist_init, coord, n, i)
            for i, w in enumerate(worker_group.workers)
        ])


def _jax_dist_init(coord, n, rank):
    import jax

    jax.distributed.initialize(coordinator_address=coord, num_processes=n,
                               process_id=rank)
    return True


class JaxTrainer(DataParallelTrainer):
    _backend_config_cls = JaxConfig

    def __init__(self, train_loop_per_worker: Callable, *,
                 jax_config: Optional[JaxConfig] = None,
                 **kwargs):
        super().__init__(train_loop_per_worker,
                         backend_config=jax_config, **kwargs)


# -- in-loop helpers (reference parity: train.torch.prepare_model etc.) ----


def prepare_mesh(scaling_config: Optional[ScalingConfig] = None,
                 mesh_config: Optional[MeshConfig] = None):
    """Build the mesh for this worker's visible devices. Inside a Train
    worker the ScalingConfig's mesh axes apply; standalone it defaults to
    all devices on the data axis."""
    cfg = mesh_config or (scaling_config.mesh_config() if scaling_config
                          else MeshConfig())
    return create_mesh(cfg)


def allreduce_gradients(grads, group_name: str = "default"):
    """Host-plane gradient mean across the worker group (gloo-DDP
    equivalent for CPU fleets; on one mesh this is unnecessary — XLA
    averages via the batch sharding)."""
    from ray_tpu.util import collective

    if session.get_session() is None or session.get_world_size() == 1:
        return grads
    return collective.allreduce_pytree(grads, group_name=group_name,
                                       op=collective.ReduceOp.MEAN)
