"""BaseTrainer: the `Trainer.fit()` contract.

Reference: `python/ray/train/base_trainer.py:53` — a Trainer wraps itself
as a Tune Trainable and runs through `Tuner` even for a single run
(`fit :540`). Here the same layering holds: `fit()` delegates to a
single-trial Tune run when the tune layer is importable, falling back to a
direct driver loop; either path produces an `air.Result`.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.air.result import Result


class BaseTrainer:
    def __init__(self, *, scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 preprocessor=None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.preprocessor = preprocessor
        self.resume_from_checkpoint = resume_from_checkpoint

    # -- subclass hooks --------------------------------------------------

    def setup(self) -> None:
        """One-time setup before training (subclass hook)."""

    def preprocess_datasets(self) -> None:
        if self.preprocessor is None:
            return
        train_ds = self.datasets.get("train")
        if train_ds is not None and getattr(
                self.preprocessor, "_is_fitted", False) is False:
            self.preprocessor.fit(train_ds)
        self.datasets = {
            k: self.preprocessor.transform(v)
            for k, v in self.datasets.items()
        }

    def training_loop(self) -> None:
        """Drive the actual training; call `session.report` with results.
        Subclasses must implement."""
        raise NotImplementedError

    # -- entry point -----------------------------------------------------

    def fit(self) -> Result:
        """Run to completion and return a Result.

        Mirrors the reference's Trainer→Tuner wrapping
        (`base_trainer.py:540`): one trial, driven by the tune layer's
        trial loop for uniform checkpoint/failure handling.
        """
        from ray_tpu.tune.trainable import wrap_trainer_as_trainable
        from ray_tpu.tune.tuner import Tuner

        trainable = wrap_trainer_as_trainable(self)
        tuner = Tuner(trainable, run_config=self.run_config)
        grid = tuner.fit()
        result = grid[0]
        if result.error and self.run_config.failure_config.fail_fast:
            raise result.error
        return result

    def as_trainable(self):
        from ray_tpu.tune.trainable import wrap_trainer_as_trainable

        return wrap_trainer_as_trainable(self)
