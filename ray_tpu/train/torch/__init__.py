"""TorchTrainer: torch DDP training on the actor runtime.

Reference: `python/ray/train/torch/` — `TorchConfig` sets up a
`torch.distributed` process group across the worker actors
(`config.py:113` `_setup_torch_process_group`; NCCL there, gloo here —
this image is CPU torch), `prepare_model` wraps in DDP
(`train_loop_utils.py:92`), `prepare_data_loader` adds a
DistributedSampler. Workers run as spawned OS processes (torch process
groups are process-global state, same constraint as jax.distributed).

On TPU fleets the flagship is `JaxTrainer` (SPMD mesh, XLA collectives);
TorchTrainer exists for CPU-side torch workloads and API parity.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer


@dataclass
class TorchConfig(BackendConfig):
    backend: str = "gloo"
    init_port: int = 7031
    timeout_s: float = 120.0
    # Process-global torch state needs one fresh OS process per rank;
    # the BackendExecutor spawns workers when this is True.
    distributed: bool = True

    def backend_cls(self):
        return TorchBackend


class TorchBackend(Backend):
    def on_training_start(self, worker_group,
                          backend_config: TorchConfig):
        import ray_tpu

        def get_ip():
            import socket

            return socket.gethostbyname(socket.gethostname())

        master = worker_group.execute_single(0, get_ip)
        n = len(worker_group)
        ray_tpu.get([
            w.execute.remote(
                _torch_dist_init, master, backend_config.init_port, n, i,
                backend_config.backend, backend_config.timeout_s)
            for i, w in enumerate(worker_group.workers)
        ])

    def on_shutdown(self, worker_group, backend_config: TorchConfig):
        import ray_tpu

        def teardown():
            import torch.distributed as dist

            if dist.is_initialized():
                dist.destroy_process_group()
            return True

        try:
            ray_tpu.get([w.execute.remote(teardown)
                         for w in worker_group.workers])
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass


def _torch_dist_init(master: str, port: int, world_size: int, rank: int,
                     backend: str, timeout_s: float):
    """Per-rank process-group bring-up (reference
    `_setup_torch_process_group`, train/torch/config.py:113)."""
    import datetime

    import torch.distributed as dist

    dist.init_process_group(
        backend=backend,
        init_method=f"tcp://{master}:{port}",
        rank=rank, world_size=world_size,
        timeout=datetime.timedelta(seconds=timeout_s))
    return True


def prepare_model(model, *, wrap_ddp: Optional[bool] = None):
    """DDP-wrap when running distributed (reference
    `train.torch.prepare_model`, train_loop_utils.py:92)."""
    import torch.distributed as dist
    from torch.nn.parallel import DistributedDataParallel

    if wrap_ddp is None:
        wrap_ddp = dist.is_initialized() and dist.get_world_size() > 1
    if wrap_ddp:
        model = DistributedDataParallel(model)
    return model


def prepare_data_loader(data_loader, *, add_dist_sampler: bool = True):
    """Rebuild a DataLoader with a DistributedSampler sharding the
    dataset across ranks (reference `prepare_data_loader`)."""
    import torch.distributed as dist
    from torch.utils.data import DataLoader, DistributedSampler

    if not (add_dist_sampler and dist.is_initialized()
            and dist.get_world_size() > 1):
        return data_loader
    sampler = DistributedSampler(data_loader.dataset)
    return DataLoader(
        data_loader.dataset,
        batch_size=data_loader.batch_size,
        sampler=sampler,
        num_workers=0,
        collate_fn=data_loader.collate_fn,
        drop_last=data_loader.drop_last,
    )


class TorchTrainer(DataParallelTrainer):
    _backend_config_cls = TorchConfig

    def __init__(self, train_loop_per_worker: Callable, *,
                 torch_config: Optional[TorchConfig] = None,
                 **kwargs: Any):
        super().__init__(train_loop_per_worker,
                         backend_config=torch_config or TorchConfig(),
                         **kwargs)


class TorchCheckpoint:
    """Reference `train/torch/torch_checkpoint.py`: model state dicts as
    AIR checkpoints."""

    @staticmethod
    def from_model(model) -> "Any":
        from ray_tpu.air import Checkpoint

        module = getattr(model, "module", model)  # unwrap DDP
        return Checkpoint.from_dict(
            {"model_state": module.state_dict()})

    @staticmethod
    def get_model(checkpoint, model):
        """Load the checkpointed state into `model`, returning it."""
        model.load_state_dict(checkpoint.to_dict()["model_state"])
        return model
