"""Gradient-boosted-tree trainers.

Reference: `python/ray/train/gbdt_trainer.py` + `train/xgboost/` /
`train/lightgbm/` — those delegate to xgboost-ray/lightgbm-ray, neither
of which (nor xgboost itself) exists in this image. The tree engine here
is sklearn's HistGradientBoosting (bundled), which matches xgboost's
histogram algorithm class; the TRAINER contract is the same as the
reference's: `datasets={"train": ds, "valid": ds}` in, per-boost-round
`session.report` metrics out, a resumable AIR checkpoint carrying the
fitted model, `fit() -> Result`.

Scaling note, honest version: classic GBDT rounds are sequential over
the full dataset; the reference distributes the HISTOGRAM build across
workers. On one host sklearn's threaded histogram build covers the same
ground, so this trainer runs the tree engine in ONE worker and uses the
cluster only for data production — the right trade until a native
distributed histogram build exists.
"""

from __future__ import annotations

import pickle
from typing import Any, Callable, Dict, Optional

import numpy as np

from ray_tpu.air import session
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer

_MODEL_KEY = "gbdt_model"


def _dataset_to_xy(ds, label_column: str):
    batches = []
    for batch in ds.iter_batches(batch_size=4096, batch_format="numpy",
                                 drop_last=False):
        batches.append(batch)
    keys = [k for k in batches[0] if k != label_column]
    X = np.concatenate([
        np.column_stack([np.asarray(b[k], np.float64).reshape(
            len(np.asarray(b[label_column])), -1) for k in keys])
        for b in batches])
    y = np.concatenate([np.asarray(b[label_column]) for b in batches])
    return X, y


class GBDTTrainer(DataParallelTrainer):
    """Shared driver for the boosted-tree trainers; subclasses pick the
    sklearn estimator the same way the reference's subclasses pick
    xgboost vs lightgbm."""

    _estimator_factory: Optional[Callable] = None
    _default_metric = "score"

    def __init__(self, *, label_column: str,
                 params: Optional[Dict[str, Any]] = None,
                 num_boost_round: int = 100, **kwargs):
        sc = kwargs.get("scaling_config")
        if sc is not None and getattr(sc, "num_workers", 1) not in (None,
                                                                    1):
            raise ValueError(
                "GBDTTrainer runs the tree engine in ONE worker (boost "
                "rounds are sequential; sklearn threads the histogram "
                "build). num_workers>1 would fit N independent models "
                "on 1/N shards each — set num_workers=1.")
        params = dict(params or {})
        params.setdefault("max_iter", num_boost_round)
        factory = self._estimator_factory  # instance attr wins (subclass
        metric_name = self._default_metric  # sets it before super())
        label = label_column

        def train_loop(config):
            train_ds = session.get_dataset_shard("train")
            valid_ds = session.get_dataset_shard("valid")
            X, y = _dataset_to_xy(train_ds, label)
            est = factory(**params)
            # Warm start from a prior checkpoint (resume semantics).
            ckpt = session.get_checkpoint()
            if ckpt is not None:
                prev = pickle.loads(ckpt.to_dict()[_MODEL_KEY])
                if hasattr(prev, "n_iter_"):
                    est.__dict__.update(prev.__dict__)
                    # AFTER the update: prev's __dict__ carries its own
                    # warm_start=False and would clobber the flag,
                    # silently retraining from scratch.
                    est.warm_start = True
            est.fit(X, y)
            metrics = {
                "train_" + metric_name: float(est.score(X, y)),
                "n_trees": int(getattr(est, "n_iter_", params["max_iter"])),
            }
            if valid_ds is not None:
                Xv, yv = _dataset_to_xy(valid_ds, label)
                metrics["valid_" + metric_name] = float(est.score(Xv, yv))
            session.report(metrics, checkpoint=Checkpoint.from_dict(
                {_MODEL_KEY: pickle.dumps(est)}))

        super().__init__(train_loop, **kwargs)

    @staticmethod
    def get_model(checkpoint: Checkpoint):
        """Fitted estimator out of a trainer checkpoint."""
        return pickle.loads(checkpoint.to_dict()[_MODEL_KEY])


class XGBoostTrainer(GBDTTrainer):
    """Boosted-tree REGRESSOR/classifier chosen by ``objective`` param
    ('regression' default, 'classification' for the classifier) —
    occupies the reference XGBoostTrainer slot."""

    _default_metric = "score"

    def __init__(self, *, params: Optional[Dict[str, Any]] = None,
                 **kwargs):
        params = dict(params or {})
        objective = params.pop("objective", "regression")

        def factory(**p):
            from sklearn.ensemble import (
                HistGradientBoostingClassifier,
                HistGradientBoostingRegressor,
            )

            cls = HistGradientBoostingClassifier \
                if objective.startswith("class") \
                else HistGradientBoostingRegressor
            return cls(**p)

        self._estimator_factory = factory  # per-instance: objectives
        super().__init__(params=params, **kwargs)  # must not leak


LightGBMTrainer = XGBoostTrainer  # same engine; both reference slots
