"""Logical-axis sharding rules → concrete `NamedSharding`s.

The reference has no analog (its FSDP support is a passthrough wrapper,
`python/ray/train/torch/train_loop_utils.py:101`); this is the GSPMD-native
replacement: model code names its array dimensions with *logical* axes
("batch", "embed", "heads", ...) and a rules table maps those to mesh axes.
Swapping parallelism strategy = swapping the rules table, with no model
changes — the property that makes TP/FSDP/SP composable.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Each rule: logical axis name -> mesh axis (str), tuple of mesh axes, or None
LogicalRules = Sequence[Tuple[str, Union[str, Tuple[str, ...], None]]]

# The canonical table for transformer LMs. Matches the axis convention in
# parallel.mesh: params shard over (fsdp, tensor); activations over
# (data+fsdp for batch, seq for sequence, tensor for heads/mlp).
DEFAULT_RULES: LogicalRules = (
    ("batch", ("data", "fsdp")),
    ("seq", "seq"),          # activation sequence dim (context parallel)
    ("embed", "fsdp"),       # param embed dim (ZeRO-3 shard)
    ("act_embed", None),     # activation embed dim: replicated — batch
                             # already consumes data+fsdp; tensor-sharding
                             # activations here would force a transpose
                             # before every matmul
    ("mlp", "tensor"),       # param/activation mlp hidden dim
    ("heads", "tensor"),     # attention heads
    ("kv_heads", "tensor"),
    ("head_dim", None),
    ("vocab", "tensor"),
    ("expert", "expert"),
    ("stage", "pipe"),
    ("norm", None),
)


def logical_to_mesh_axes(logical_axes: Sequence[Optional[str]],
                         rules: LogicalRules = DEFAULT_RULES) -> P:
    """Map a tuple of logical axis names (None = replicated) to a
    PartitionSpec under the given rules."""
    table = dict(rules)
    out = []
    for ax in logical_axes:
        if ax is None:
            out.append(None)
        else:
            out.append(table.get(ax))
    # Trailing Nones are dropped for a tidier spec.
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def named_sharding(mesh: Mesh, *logical_axes: Optional[str],
                   rules: LogicalRules = DEFAULT_RULES) -> NamedSharding:
    spec = logical_to_mesh_axes(logical_axes, rules)
    return NamedSharding(mesh, spec)


def tree_shardings(mesh: Mesh, logical_tree,
                   rules: LogicalRules = DEFAULT_RULES):
    """Map a pytree of logical-axis tuples to a pytree of NamedShardings."""
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, logical_to_mesh_axes(axes, rules)),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x
        ),
    )


def shard_pytree(tree, mesh: Mesh, logical_tree,
                 rules: LogicalRules = DEFAULT_RULES):
    """Place a pytree of host arrays onto the mesh with the given logical
    axis annotations (pytree of tuples, same structure)."""
    shardings = tree_shardings(mesh, logical_tree, rules)
    return jax.tree.map(lambda x, s: jax.device_put(x, s), tree, shardings)


def with_logical_constraint(x, *logical_axes: Optional[str],
                            mesh: Optional[Mesh] = None,
                            rules: LogicalRules = DEFAULT_RULES):
    """`lax.with_sharding_constraint` in logical-axis vocabulary.

    Inside jit the mesh comes from the surrounding context when omitted
    (requires the mesh's axis names to be bound, e.g. via
    `jax.sharding.use_mesh` or in/out shardings on the jit).
    """
    spec = logical_to_mesh_axes(logical_axes, rules)
    if mesh is not None:
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except Exception:
        return x  # no mesh in scope → single-device path, constraint is moot
