"""Device-mesh construction with named parallelism axes.

The reference forms its parallel groups imperatively
(`torch.distributed.init_process_group(nccl)` at
`python/ray/train/torch/config.py:113`; NCCL groups in
`python/ray/util/collective/collective.py`). On TPU the idiomatic unit is a
`jax.sharding.Mesh` over the ICI torus: collectives are inserted by XLA from
sharding annotations, so the framework's job reduces to (a) choosing a mesh
shape whose fast-varying axes map onto ICI neighbours and (b) handing that
mesh to compiled programs. This module owns (a).

Axis convention (outer → inner, i.e. slowest → fastest varying):

    data   — pure data parallelism (replicated params); may span DCN
    fsdp   — data parallelism with parameter/optimizer sharding (ZeRO-3)
    expert — expert parallelism for MoE layers
    pipe   — pipeline-parallel stages
    seq    — sequence/context parallelism (ring attention / Ulysses)
    tensor — tensor (operator) parallelism; innermost so TP collectives
             ride single-hop ICI links

``tensor`` last matters: `mesh_utils.create_device_mesh` assigns physically
adjacent chips to the fastest-varying mesh dimension, and tensor-parallel
collectives (all-reduce per layer) are the most latency-sensitive.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, fields
from typing import Optional, Sequence

import numpy as np

AXIS_NAMES = ("data", "fsdp", "expert", "pipe", "seq", "tensor")


@dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh shape. Zero/negative → auto-fill from device count.

    The Train-layer `ScalingConfig` lowers its per-axis worker counts to one
    of these; users of the parallel layer can also build one directly.
    """

    data: int = -1  # -1: absorb remaining devices
    fsdp: int = 1
    expert: int = 1
    pipe: int = 1
    seq: int = 1
    tensor: int = 1

    def axis_sizes(self, n_devices: int) -> dict:
        sizes = {f.name: getattr(self, f.name) for f in fields(self)}
        fixed = math.prod(v for v in sizes.values() if v > 0)
        free = [k for k, v in sizes.items() if v <= 0]
        if not free:
            if fixed != n_devices:
                raise ValueError(
                    f"mesh {sizes} needs {fixed} devices, have {n_devices}"
                )
            return sizes
        if len(free) > 1:
            raise ValueError(f"at most one mesh axis may be auto (-1): {free}")
        if n_devices % fixed != 0:
            raise ValueError(
                f"cannot factor {n_devices} devices into mesh {sizes}"
            )
        sizes[free[0]] = n_devices // fixed
        return sizes

    def shape(self, n_devices: int) -> tuple:
        s = self.axis_sizes(n_devices)
        return tuple(s[a] for a in AXIS_NAMES)


def mesh_shape_for(n_devices: int, *, model_params: Optional[int] = None,
                   seq_len: Optional[int] = None) -> MeshConfig:
    """Heuristic mesh for a given device count and model/sequence size.

    Small models → pure data parallel. Models too big for one chip's HBM →
    fsdp. Very long sequences → carve a ``seq`` axis. This mirrors what the
    scaling-book recipe does by hand: pick the cheapest sharding that fits.
    """
    fsdp = 1
    seq = 1
    if model_params is not None:
        # ~18 bytes/param for bf16 params + f32 grads + adam moments.
        bytes_needed = model_params * 18
        per_chip_hbm = 14 * 2**30  # conservative v5e figure (16G - headroom)
        fsdp = max(1, 2 ** math.ceil(math.log2(max(1, bytes_needed // per_chip_hbm + 1))))
        fsdp = min(fsdp, n_devices)
        while n_devices % fsdp:
            fsdp *= 2
        fsdp = min(fsdp, n_devices)
    if seq_len is not None and seq_len >= 32768:
        seq = min(max(1, seq_len // 32768), max(1, n_devices // fsdp))
        while (n_devices // fsdp) % seq:
            seq -= 1
    return MeshConfig(data=-1, fsdp=fsdp, seq=seq)


def create_mesh(config: Optional[MeshConfig] = None,
                devices: Optional[Sequence] = None,
                axis_names: Sequence[str] = AXIS_NAMES):
    """Build a `jax.sharding.Mesh` with the canonical axis names.

    On real TPU hardware the device order comes from
    `jax.experimental.mesh_utils.create_device_mesh`, which matches mesh
    dims to the physical ICI torus; on CPU/virtual meshes we fall back to a
    plain reshape.
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    config = config or MeshConfig()
    shape = config.shape(len(devices))
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names=tuple(axis_names))


def local_mesh(axis_names: Sequence[str] = AXIS_NAMES):
    """A 1×...×1 mesh over a single device — lets sharded code paths run
    unmodified on one chip (all collectives become no-ops)."""
    import jax

    return create_mesh(MeshConfig(data=1), devices=jax.devices()[:1],
                       axis_names=axis_names)
