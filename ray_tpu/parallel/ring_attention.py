"""Ring attention: context parallelism over a mesh axis.

Absent from the reference (SURVEY.md §5 "Long-context / sequence
parallelism": *not present*; our charter requires it first-class). Design:
the sequence dimension is sharded over the ``seq`` mesh axis; each device
holds one contiguous chunk of Q, K, V. K/V chunks rotate around the ring via
`lax.ppermute` (single-hop ICI neighbours) while each device accumulates
flash-style online-softmax partial results for its resident Q chunk. Compute
on step i overlaps with the DMA of step i+1's K/V — XLA schedules the
ppermute asynchronously, so for chunk sizes that keep the MXU busy the ring
is bandwidth-hidden.

Math (per Q row): maintain running max m, normalizer l, accumulator o.
For each incoming K/V block with scores s:
    m' = max(m, rowmax(s));  p = exp(s - m') (masked entries forced to 0)
    l  = l * exp(m - m') + rowsum(p)
    o  = o * exp(m - m') + p @ V
Final output o / l. Causality is decided per (q_chunk, kv_chunk) pair:
kv_chunk > q_chunk → fully masked (contributes nothing), kv_chunk ==
q_chunk → intra-chunk causal mask, kv_chunk < q_chunk → unmasked.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax

from ray_tpu.parallel.collectives import axis_size as _axis_size, shard_map
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _block_attn(q, k, v, m, l, o, mask):
    """One online-softmax accumulation step.

    q: [B, Sq, H, D]; k/v: [B, Sk, H, D]; mask: [Sq, Sk] bool or None.
    m, l: [B, H, Sq]; o: [B, Sq, H, D]. All accumulation in f32.
    """
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None, :, :], s, _NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # Guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1.
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        p = jnp.where(mask[None, None, :, :], p, 0.0)
    correction = jnp.exp(m - m_new)
    l_new = l * correction + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def _ring_attention_sharded(q, k, v, axis_name: str, causal: bool):
    """Body executed per-shard under shard_map. Shapes are local chunks."""
    axis_size = _axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    sk = k.shape[1]

    q32 = q.astype(jnp.float32)
    m0 = jnp.full((b, h, sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    o0 = jnp.zeros((b, sq, h, d), jnp.float32)

    # Intra-chunk causal mask, used only when kv_chunk == q_chunk. Global
    # positions: q row r is my_idx*sq + r, kv col c is kv_idx*sk + c; with
    # equal chunk sizes the diagonal comparison reduces to r >= c.
    diag_mask = (jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]) \
        if causal else None

    def step(carry, r):
        m, l, o, k_cur, v_cur = carry
        kv_idx = (my_idx - r) % axis_size  # origin chunk of current k/v
        if causal:
            # Select mask regime without data-dependent control flow:
            # full-visible (ones), diagonal, or hidden (zeros).
            full = kv_idx < my_idx
            hidden = kv_idx > my_idx
            mask = jnp.where(
                hidden, False, jnp.where(full, True, diag_mask)
            )
        else:
            mask = None
        m, l, o = _block_attn(q32, k_cur.astype(jnp.float32),
                              v_cur, m, l, o, mask)
        # Rotate k/v to the next device; skip on the last step.
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (m, l, o, k_nxt, v_nxt), None

    (m, l, o, _, _), _ = lax.scan(
        step, (m0, l0, o0, k, v), jnp.arange(axis_size)
    )
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, *, mesh: Optional[Mesh] = None,
                   axis_name: str = "seq", causal: bool = True):
    """Context-parallel attention. q/k/v: [batch, seq, heads, head_dim],
    sequence dim sharded over `axis_name`.

    Called under an active mesh context (inside shard_map/jit with the axis
    bound) it runs per-shard directly; given a `mesh` it wraps itself in
    shard_map with batch over (data, fsdp), heads over tensor, seq over
    `axis_name`.
    """
    if mesh is None:
        return _ring_attention_sharded(q, k, v, axis_name, causal)
    spec = P(("data", "fsdp"), axis_name, "tensor", None)
    fn = shard_map(
        functools.partial(_ring_attention_sharded, axis_name=axis_name,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def reference_attention(q, k, v, causal: bool = True):
    """Unsharded reference implementation (for tests and 1-device paths)."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)
