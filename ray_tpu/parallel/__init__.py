"""TPU-native parallelism: device meshes, sharding rules, collectives,
and long-context (sequence/context) parallelism.

This package is the TPU answer to the reference's collective substrate
(`python/ray/util/collective/`, NCCL/Gloo groups — see SURVEY.md §5) and to
the parallelism strategies Ray delegates to torch DDP/FSDP
(`python/ray/train/torch/train_loop_utils.py:92-101`). Instead of process
groups + NCCL calls, parallelism here is expressed as a `jax.sharding.Mesh`
with named axes and XLA collectives inside compiled programs:

- ``mesh``      — mesh axes (data/fsdp/expert/pipe/seq/tensor) and creation
- ``sharding``  — logical-axis → mesh-axis rules, NamedSharding helpers
- ``collectives`` — in-program collective wrappers (psum/all_gather/...)
- ``ring_attention`` — ring/context parallel attention (absent from the
  reference entirely; SURVEY.md §5 "Long-context")
- ``ulysses``   — all-to-all (DeepSpeed-Ulysses style) sequence parallelism
- ``pipeline``  — pipeline parallel microbatching over a ``pipe`` mesh axis
"""

from ray_tpu.parallel.mesh import (  # noqa: F401
    MeshConfig,
    create_mesh,
    mesh_shape_for,
    local_mesh,
)
from ray_tpu.parallel.sharding import (  # noqa: F401
    LogicalRules,
    DEFAULT_RULES,
    logical_to_mesh_axes,
    named_sharding,
    shard_pytree,
    with_logical_constraint,
)
from ray_tpu.parallel.ring_attention import ring_attention  # noqa: F401
from ray_tpu.parallel.ulysses import ulysses_attention  # noqa: F401
from ray_tpu.parallel.pipeline import (  # noqa: F401
    pipeline_apply,
    pipeline_train_1f1b,
    schedule_info,
)
