"""Pipeline parallelism over the ``pipe`` mesh axis.

The reference has no in-tree pipeline parallelism (only the Alpa release
test, `release/alpa_tests/train_opt_2_7b_minimum.py:95` — SURVEY.md §2
parallelism inventory). Here PP is a first-class mesh axis: stage
parameters are sharded over ``pipe`` (each device group holds one stage)
and microbatches stream through a `lax.scan` whose carried state rotates
between neighbouring stages via `lax.ppermute` — the standard SPMD
"collective pipeline" formulation, which keeps everything inside one XLA
program (no host round-trips between stages, unlike actor-staged PP).

Two schedules:

- ``pipeline_apply`` — GPipe fill/drain, forward only (inference /
  autodiff-through-the-scan). S+M-1 ticks; bubble (S-1)/(S+M-1).
- ``pipeline_train_1f1b`` — interleaved one-forward-one-backward
  TRAINING schedule (Megatron-style 1F1B, the synchronized-collective
  variant): every tick runs one forward sub-slot and one backward
  sub-slot on every stage, activations ppermute right while gradients
  ppermute left, and the backward of microbatch m starts as soon as its
  loss gradient exists — S-1 ticks after injection, NOT after all M
  forwards. The activation stash per stage is therefore bounded by
  ``min(M, 2(S-1)+1)`` microbatch INPUTS (constant in M; GPipe-through-
  autodiff stashes all M), with the stage forward rematerialized from
  the stashed input during its backward sub-slot. Total ticks
  M + 2(S-1): bubble fraction 2(S-1)/(M + 2(S-1)), the 1F1B bound.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from ray_tpu.parallel.collectives import axis_size as _axis_size, shard_map
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_sharded(stage_params, x_mb, stage_fn: Callable,
                      axis_name: str):
    """Per-shard body. stage_params: this stage's params (local). x_mb:
    [M, mb, ...] microbatched input — only stage 0's copy is consumed.
    Returns [M, mb, ...] outputs (valid on the last stage; replicated back
    by the caller via ppermute)."""
    n_stages = _axis_size(axis_name)
    stage_idx = lax.axis_index(axis_name)
    n_mb = x_mb.shape[0]
    ticks = n_stages + n_mb - 1

    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        state, outputs = carry
        # Which microbatch does stage 0 inject this tick?
        mb_idx = jnp.clip(t, 0, n_mb - 1)
        injected = lax.dynamic_index_in_dim(x_mb, mb_idx, axis=0,
                                            keepdims=False)
        inp = jnp.where(stage_idx == 0, injected, state)
        out = stage_fn(stage_params, inp)
        # Last stage records its result at slot t - (n_stages - 1).
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_mb - 1)
        is_valid = (t >= n_stages - 1) & (stage_idx == n_stages - 1)
        current = lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(is_valid, out, current), out_idx, 0
        )
        # Shift activations to the next stage.
        state = lax.ppermute(out, axis_name, fwd_perm)
        return (state, outputs), None

    state0 = jnp.zeros_like(stage_fn(stage_params,
                                     jax.tree.map(lambda a: a[0], x_mb)))
    outputs0 = jnp.zeros((n_mb,) + state0.shape, state0.dtype)
    (_, outputs), _ = lax.scan(tick, (state0, outputs0),
                               jnp.arange(ticks))
    # Broadcast final outputs from the last stage to all stages so the
    # caller sees a replicated result (psum over one-hot contribution).
    contribution = jnp.where(stage_idx == n_stages - 1, outputs,
                             jnp.zeros_like(outputs))
    return lax.psum(contribution, axis_name)


def pipeline_apply(stage_fn: Callable, stage_params, x_microbatches, *,
                   mesh: Optional[Mesh] = None, axis_name: str = "pipe"):
    """Run `stage_fn(params, x)` as a pipeline over `axis_name`.

    - `stage_params`: pytree whose leaves have a leading stage dimension of
      size n_stages, sharded over `axis_name` (each shard sees its own
      stage's slice with the stage dim collapsed).
    - `x_microbatches`: [num_microbatches, microbatch, ...] input,
      replicated over `axis_name`.
    Returns outputs [num_microbatches, microbatch, ...], replicated.
    """
    body = functools.partial(_pipeline_sharded, stage_fn=stage_fn,
                             axis_name=axis_name)
    if mesh is None:
        return body(stage_params, x_microbatches)
    param_spec = jax.tree.map(lambda _: P(axis_name), stage_params)
    fn = shard_map(
        lambda p, x: body(jax.tree.map(lambda a: a[0], p), x),
        mesh=mesh,
        in_specs=(param_spec, P()),
        out_specs=P(),
        check_vma=False,
    )
    return fn(stage_params, x_microbatches)


def schedule_info(n_stages: int, n_microbatches: int) -> Dict[str, Any]:
    """Static properties of the 1F1B schedule — what the tests and the
    dryrun assert: tick count, per-stage stash bound, bubble fraction."""
    ticks = n_microbatches + 2 * (n_stages - 1)
    return {
        "ticks": ticks,
        "stash_slots": min(n_microbatches, 2 * (n_stages - 1) + 1),
        "bubble_fraction": 2 * (n_stages - 1) / ticks,
    }


def _tree_where(pred, a, b):
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)


def _tree_acc(acc, delta, valid):
    return jax.tree.map(
        lambda a, d: a + jnp.where(valid, d, jnp.zeros_like(d)),
        acc, delta)


def _1f1b_sharded(stage_params, head_params, x_mb, aux_mb, *,
                  stage_fn: Callable, head_loss_fn: Callable,
                  n_stages: int, axis_name: str):
    """Per-shard 1F1B body. stage_params: THIS stage's slice (no stage
    dim). x_mb: [M, mb, ...] pipeline input activations (replicated).
    aux_mb: [M, ...] per-microbatch head targets. Returns (mean loss,
    d stage_params (local), d head_params, d x_mb) — loss/dhead/dx
    replicated via psum, dstage left per-shard.

    Known compute trade of the homogeneous-SPMD formulation: every
    stage executes both the last-stage path (head fwd+bwd) and the
    interior path (stage vjp) each tick, with `where`-selects keeping
    one. `lax.cond` cannot help — its predicate is device-varying here,
    which lowers to a select executing both branches anyway. Removing
    the waste needs per-stage program heterogeneity (one jit per stage
    + explicit send/recv), a different architecture. The schedule's
    wins (bounded stash, in-program collectives, zero host round-trips)
    hold; budget roughly 2x stage FLOPs + one head fwd+bwd per tick."""
    S = n_stages
    s = lax.axis_index(axis_name)
    M = x_mb.shape[0]
    BUF = min(M, 2 * (S - 1) + 1)
    T = M + 2 * (S - 1)
    fwd_perm = [(i, i + 1) for i in range(S - 1)]
    bwd_perm = [(i + 1, i) for i in range(S - 1)]
    is_last = s == S - 1
    is_first = s == 0

    def fwd_and_loss(hp, sp, x, aux):
        y = stage_fn(sp, x)
        return head_loss_fn(hp, y, aux)

    def tick(carry, t):
        (a_state, g_state, x_buf, dstage, dhead, dx_mb,
         loss_acc) = carry
        # ---- forward sub-slot: stage s forwards microbatch t - s.
        fm = t - s
        f_valid = (fm >= 0) & (fm < M)
        fm_c = jnp.clip(fm, 0, M - 1)
        x_inj = lax.dynamic_index_in_dim(x_mb, fm_c, 0, keepdims=False)
        x_in = jnp.where(is_first, x_inj, a_state)
        y = stage_fn(stage_params, x_in)
        slot_f = jnp.mod(fm_c, BUF)
        prev = lax.dynamic_index_in_dim(x_buf, slot_f, 0,
                                        keepdims=False)
        x_buf = lax.dynamic_update_index_in_dim(
            x_buf, jnp.where(f_valid, x_in, prev), slot_f, 0)
        # ---- backward sub-slot: stage s backwards microbatch
        # t - 2(S-1) + s (for the LAST stage that is the microbatch it
        # just forwarded — its loss gradient is born this tick).
        bm = t - 2 * (S - 1) + s
        b_valid = (bm >= 0) & (bm < M)
        bm_c = jnp.clip(bm, 0, M - 1)
        x_saved = lax.dynamic_index_in_dim(
            x_buf, jnp.mod(bm_c, BUF), 0, keepdims=False)
        aux = jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, bm_c, 0,
                                               keepdims=False), aux_mb)
        # Last stage: loss + its gradients seed the backward wave.
        (loss_m, (dh, dsp_last, dx_last)) = jax.value_and_grad(
            fwd_and_loss, argnums=(0, 1, 2))(
            head_params, stage_params, x_saved, aux)
        # Interior stages: VJP against the gradient from the right.
        _, vjp = jax.vjp(stage_fn, stage_params, x_saved)
        dsp_mid, dx_mid = vjp(g_state)
        dsp = _tree_where(is_last, dsp_last, dsp_mid)
        dx = jnp.where(is_last, dx_last, dx_mid)
        dstage = _tree_acc(dstage, dsp, b_valid)
        dhead = _tree_acc(dhead, dh, b_valid & is_last)
        loss_acc = loss_acc + jnp.where(b_valid & is_last,
                                        loss_m, 0.0)
        dx_cur = lax.dynamic_index_in_dim(dx_mb, bm_c, 0,
                                          keepdims=False)
        dx_mb = lax.dynamic_update_index_in_dim(
            dx_mb, jnp.where(b_valid & is_first, dx, dx_cur), bm_c, 0)
        # ---- communicate: activations right, gradients left.
        a_state = lax.ppermute(y, axis_name, fwd_perm)
        g_state = lax.ppermute(dx, axis_name, bwd_perm)
        return (a_state, g_state, x_buf, dstage, dhead, dx_mb,
                loss_acc), None

    mb_shape = x_mb.shape[1:]
    zeros_mb = jnp.zeros(mb_shape, x_mb.dtype)
    carry0 = (
        zeros_mb,                                   # a_state
        zeros_mb,                                   # g_state
        jnp.zeros((BUF,) + mb_shape, x_mb.dtype),   # x_buf
        jax.tree.map(jnp.zeros_like, stage_params),  # dstage
        jax.tree.map(jnp.zeros_like, head_params),   # dhead
        jnp.zeros_like(x_mb),                        # dx_mb
        jnp.float32(0.0),                            # loss_acc
    )
    (_, _, _, dstage, dhead, dx_mb, loss_acc), _ = lax.scan(
        tick, carry0, jnp.arange(T))
    # Loss / head grads / input grads live on one stage each — psum
    # replicates them (contributions elsewhere are zero by masking).
    loss = lax.psum(loss_acc, axis_name) / M
    dhead = jax.tree.map(lambda a: lax.psum(a, axis_name) / M, dhead)
    dx_mb = lax.psum(dx_mb, axis_name) / M
    dstage = jax.tree.map(lambda a: a / M, dstage)
    return loss, dstage, dhead, dx_mb


def pipeline_train_1f1b(stage_fn: Callable, head_loss_fn: Callable,
                        stage_params, head_params, x_mb, aux_mb, *,
                        mesh: Optional[Mesh] = None,
                        axis_name: str = "pipe",
                        n_stages: Optional[int] = None
                        ) -> Tuple[Any, Any, Any, Any]:
    """Interleaved 1F1B TRAINING step over the ``axis_name`` mesh axis.

    - ``stage_fn(stage_slice, x) -> y``: one homogeneous pipeline stage
      (e.g. a stack of transformer layers via an inner scan).
    - ``head_loss_fn(head_params, y, aux) -> scalar``: the loss head
      applied to the LAST stage's output (final norm + projection + CE
      for an LM); its gradient seeds the backward wave.
    - ``stage_params``: pytree with a leading stage dimension of size S,
      sharded over ``axis_name``.
    - ``x_mb``: [M, microbatch, ...] pipeline input activations
      (embeddings computed outside), replicated.
    - ``aux_mb``: [M, ...] per-microbatch targets, replicated.

    Returns ``(mean_loss, d_stage_params (stage-stacked, sharded like
    stage_params), d_head_params, d_x_mb)`` — everything needed to
    apply an optimizer update and to continue the backward into the
    (outside) embedding.
    """
    if mesh is not None and n_stages is None:
        n_stages = mesh.shape[axis_name]
    if n_stages is None:
        raise ValueError("pass mesh or n_stages")
    body = functools.partial(
        _1f1b_sharded, stage_fn=stage_fn, head_loss_fn=head_loss_fn,
        n_stages=n_stages, axis_name=axis_name)
    if mesh is None:
        return body(stage_params, head_params, x_mb, aux_mb)
    param_spec = jax.tree.map(lambda _: P(axis_name), stage_params)
    rep = jax.tree.map(lambda _: P(), head_params)
    def _shard_body(sp, hp, x, aux):
        loss, dstage, dhead, dx = body(
            jax.tree.map(lambda a: a[0], sp), hp, x, aux)
        # Re-add the unit stage axis so the out-spec concatenation over
        # `pipe` rebuilds the stage-stacked layout of stage_params.
        return loss, jax.tree.map(lambda a: a[None], dstage), dhead, dx

    fn = shard_map(
        _shard_body,
        mesh=mesh,
        in_specs=(param_spec, rep, P(), P()),
        out_specs=(P(), jax.tree.map(lambda _: P(axis_name),
                                     stage_params), rep, P()),
        check_vma=False,
    )
    loss, dstage, dhead, dx = fn(stage_params, head_params, x_mb,
                                 aux_mb)
    return loss, dstage, dhead, dx


def llama_pp_parts(cfg, params, *, n_stages: int):
    """Split llama parameters into 1F1B pipeline pieces.

    Returns ``(stage_params, head_params, stage_fn, head_loss_fn,
    embed_fn)``: the transformer blocks become ``n_stages`` homogeneous
    stages (each an inner scan over n_layers/n_stages blocks, stacked on
    a leading stage axis for the ``pipe`` sharding); the final norm +
    output projection + next-token CE form the loss head that seeds the
    backward wave; the embedding runs OUTSIDE the pipeline (replicated),
    with its gradient recoverable from the returned d_x_mb.
    """
    from ray_tpu.models import llama as _llama
    from ray_tpu.ops.norms import rms_norm_reference
    from ray_tpu.ops.rope import rope_frequencies

    L = cfg.n_layers
    if L % n_stages:
        raise ValueError(f"n_layers={L} not divisible by "
                         f"n_stages={n_stages}")
    per = L // n_stages
    stage_params = jax.tree.map(
        lambda a: a.reshape((n_stages, per) + a.shape[1:]),
        params["layers"])
    head_params = {"final_norm": params["final_norm"]}
    if "out" in params:
        head_params["out"] = params["out"]
    else:  # tied embeddings project through embed.T
        head_params["out_t"] = params["embed"]
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                cfg.rope_theta)

    def stage_fn(layers_slice, x):
        def body(h, lp):
            return _llama.layer_fn(cfg, None, _llama.DEFAULT_RULES,
                                   cos, sin, h, lp, None), None

        x, _ = lax.scan(body, x, layers_slice)
        return x

    def head_loss_fn(hp, y, tokens):
        h = rms_norm_reference(y, hp["final_norm"], cfg.norm_eps)
        w = hp["out"] if "out" in hp else hp["out_t"].T
        logits = jnp.einsum("btd,dv->btv", h.astype(jnp.float32),
                            w.astype(jnp.float32))
        logp = jax.nn.log_softmax(logits[:, :-1])
        tgt = tokens[:, 1:]
        nll = -jnp.take_along_axis(logp, tgt[..., None], -1)[..., 0]
        return nll.mean()

    def embed_fn(embed, tokens):
        return embed[tokens].astype(cfg.dtype)

    return stage_params, head_params, stage_fn, head_loss_fn, embed_fn
