"""Pipeline parallelism over the ``pipe`` mesh axis.

The reference has no in-tree pipeline parallelism (only the Alpa release
test, `release/alpa_tests/train_opt_2_7b_minimum.py:95` — SURVEY.md §2
parallelism inventory). Here PP is a first-class mesh axis: stage
parameters are sharded over ``pipe`` (each device group holds one stage)
and microbatches stream through a `lax.scan` whose carried state rotates
between neighbouring stages via `lax.ppermute` — the standard SPMD
"collective pipeline" formulation, which keeps everything inside one XLA
program (no host round-trips between stages, unlike actor-staged PP).

Schedule: GPipe-style fill/drain. For S stages and M microbatches the scan
runs S+M-1 ticks; tick t has stage s working on microbatch t-s. Bubble
fraction (S-1)/(S+M-1) — callers pick M >= 4*S to amortize.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_sharded(stage_params, x_mb, stage_fn: Callable,
                      axis_name: str):
    """Per-shard body. stage_params: this stage's params (local). x_mb:
    [M, mb, ...] microbatched input — only stage 0's copy is consumed.
    Returns [M, mb, ...] outputs (valid on the last stage; replicated back
    by the caller via ppermute)."""
    n_stages = lax.axis_size(axis_name)
    stage_idx = lax.axis_index(axis_name)
    n_mb = x_mb.shape[0]
    ticks = n_stages + n_mb - 1

    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        state, outputs = carry
        # Which microbatch does stage 0 inject this tick?
        mb_idx = jnp.clip(t, 0, n_mb - 1)
        injected = lax.dynamic_index_in_dim(x_mb, mb_idx, axis=0,
                                            keepdims=False)
        inp = jnp.where(stage_idx == 0, injected, state)
        out = stage_fn(stage_params, inp)
        # Last stage records its result at slot t - (n_stages - 1).
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_mb - 1)
        is_valid = (t >= n_stages - 1) & (stage_idx == n_stages - 1)
        current = lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(is_valid, out, current), out_idx, 0
        )
        # Shift activations to the next stage.
        state = lax.ppermute(out, axis_name, fwd_perm)
        return (state, outputs), None

    state0 = jnp.zeros_like(stage_fn(stage_params,
                                     jax.tree.map(lambda a: a[0], x_mb)))
    outputs0 = jnp.zeros((n_mb,) + state0.shape, state0.dtype)
    (_, outputs), _ = lax.scan(tick, (state0, outputs0),
                               jnp.arange(ticks))
    # Broadcast final outputs from the last stage to all stages so the
    # caller sees a replicated result (psum over one-hot contribution).
    contribution = jnp.where(stage_idx == n_stages - 1, outputs,
                             jnp.zeros_like(outputs))
    return lax.psum(contribution, axis_name)


def pipeline_apply(stage_fn: Callable, stage_params, x_microbatches, *,
                   mesh: Optional[Mesh] = None, axis_name: str = "pipe"):
    """Run `stage_fn(params, x)` as a pipeline over `axis_name`.

    - `stage_params`: pytree whose leaves have a leading stage dimension of
      size n_stages, sharded over `axis_name` (each shard sees its own
      stage's slice with the stage dim collapsed).
    - `x_microbatches`: [num_microbatches, microbatch, ...] input,
      replicated over `axis_name`.
    Returns outputs [num_microbatches, microbatch, ...], replicated.
    """
    body = functools.partial(_pipeline_sharded, stage_fn=stage_fn,
                             axis_name=axis_name)
    if mesh is None:
        return body(stage_params, x_microbatches)
    param_spec = jax.tree.map(lambda _: P(axis_name), stage_params)
    fn = jax.shard_map(
        lambda p, x: body(jax.tree.map(lambda a: a[0], p), x),
        mesh=mesh,
        in_specs=(param_spec, P()),
        out_specs=P(),
        check_vma=False,
    )
    return fn(stage_params, x_microbatches)
