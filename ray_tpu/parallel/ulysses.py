"""Ulysses-style (all-to-all) sequence parallelism.

Second context-parallel scheme (complement to ring attention; absent from
the reference — SURVEY.md §5). Activations arrive sequence-sharded
[B, S/P, H, D]; two all-to-alls re-shard to head-sharded [B, S, H/P, D] so
each device runs *full-sequence* attention over a subset of heads, then the
layout is restored. Preferred over ring attention when heads % P == 0 and
the sequence fits HBM after gathering — the all-to-alls move each element
twice total vs. P-1 ppermutes of K/V, and the attention itself needs no
online-softmax bookkeeping.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional


from ray_tpu.parallel.collectives import shard_map
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.parallel.ring_attention import reference_attention


def _ulysses_sharded(q, k, v, axis_name: str, causal: bool,
                     attn_fn: Optional[Callable]):
    # [B, S/P, H, D] -> [B, S, H/P, D]: split heads (axis 2), concat seq (1).
    def scatter_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def scatter_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    attn = attn_fn or functools.partial(reference_attention, causal=causal)
    out = attn(qh, kh, vh)
    return scatter_seq(out)


def ulysses_attention(q, k, v, *, mesh: Optional[Mesh] = None,
                      axis_name: str = "seq", causal: bool = True,
                      attn_fn: Optional[Callable] = None):
    """All-to-all sequence-parallel attention.

    q/k/v: [batch, seq, heads, head_dim] with seq sharded over `axis_name`.
    `attn_fn` lets callers swap in the Pallas flash kernel for the inner
    full-sequence attention. Requires heads % axis_size == 0.
    """
    if mesh is None:
        return _ulysses_sharded(q, k, v, axis_name, causal, attn_fn)
    spec = P(("data", "fsdp"), axis_name, "tensor", None)
    fn = shard_map(
        functools.partial(_ulysses_sharded, axis_name=axis_name,
                          causal=causal, attn_fn=attn_fn),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
