"""In-program collective wrappers.

The reference's `ray.util.collective` (`util/collective/collective.py:258-615`)
offers allreduce/allgather/reducescatter/broadcast/barrier/send/recv between
actors via NCCL/Gloo *at runtime*. The TPU-native equivalents are XLA
collectives *inside compiled programs* — `lax.psum` and friends under
`shard_map`/`pjit` — which XLA schedules onto ICI. These wrappers exist to
give that surface one place (naming parity with the reference, and a couple
of conveniences like axis-group handling), plus host-level helpers for the
rare out-of-program exchange.

An actor-level runtime collective API (process groups over the object plane,
for host-side data) lives in `ray_tpu.util.collective`.
"""

from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

AxisName = Union[str, Sequence[str]]


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable ``shard_map``: newer jax exposes ``jax.shard_map``
    (with ``check_vma``); 0.4.x only has the experimental module (where
    the same knob is ``check_rep``). Every per-shard kernel in this
    package routes through here so a jax upgrade/downgrade is one-file."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def allreduce(x, axis_name: AxisName, op: str = "sum"):
    """Reference parity: `collective.allreduce` (collective.py:258)."""
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    raise ValueError(f"unsupported reduce op: {op}")


def allgather(x, axis_name: AxisName, axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reducescatter(x, axis_name: AxisName, axis: int = 0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def broadcast(x, axis_name: AxisName, root: int = 0):
    """Every shard gets root's value. XLA has no bcast primitive; select the
    root's contribution then sum (dead data is DCE'd into an efficient
    collective)."""
    idx = lax.axis_index(axis_name)
    contribution = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(contribution, axis_name)


def all_to_all(x, axis_name: AxisName, split_axis: int, concat_axis: int):
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def permute(x, axis_name: AxisName, shift: int = 1):
    """Ring shift by `shift` positions (the ring-attention building block)."""
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def send_recv(x, axis_name: AxisName, pairs: Sequence[tuple]):
    """Point-to-point as a sparse permute: `pairs` is [(src, dst), ...]."""
    return lax.ppermute(x, axis_name, list(pairs))


def axis_index(axis_name: AxisName):
    return lax.axis_index(axis_name)


def axis_size(axis_name: AxisName):
    """Static size of a named mesh axis. ``lax.axis_size`` only exists
    on newer jax; on 0.4.x the canonical idiom is ``psum(1, axis)``,
    which constant-folds to a Python int for non-traced operands."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def barrier(axis_name: AxisName):
    """Synchronization point; inside XLA programs ordering is handled by the
    compiler, so this is only meaningful as an optimization barrier."""
    token = lax.psum(jnp.zeros((), jnp.float32), axis_name)
    return token


# ---------------------------------------------------------------------------
# Host-level (out-of-program) helpers
# ---------------------------------------------------------------------------


def host_broadcast(tree, mesh, logical_axes=None):
    """Replicate a host pytree onto every device of a mesh."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)


def host_allgather(x):
    """Gather a fully-addressable sharded array back to the host."""
    return jax.device_get(x)
