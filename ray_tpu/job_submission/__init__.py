"""Job submission: run entrypoint commands as supervised jobs.

Reference: `dashboard/modules/job/` (SURVEY.md §2.2) — `JobManager`
(`job_manager.py:490`) spawns a detached `JobSupervisor` actor (`:136`)
per job that runs the entrypoint as a subprocess, captures logs, and
records `JobInfo`; the SDK (`python/ray/job_submission/`) talks to it.
Here the same actor architecture runs in-process; the HTTP surface is
exposed by `ray_tpu.dashboard`.
"""

from __future__ import annotations

import os
import subprocess
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import ray_tpu


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


@dataclass
class JobInfo:
    job_id: str
    entrypoint: str
    status: str = JobStatus.PENDING
    message: str = ""
    start_time: Optional[float] = None
    end_time: Optional[float] = None
    metadata: Dict[str, str] = field(default_factory=dict)
    runtime_env: Optional[dict] = None
    return_code: Optional[int] = None


@ray_tpu.remote
class JobSupervisor:
    """One per job: runs the entrypoint subprocess, buffers logs."""

    def __init__(self, job_id: str, entrypoint: str,
                 runtime_env: Optional[dict], metadata: Dict[str, str]):
        self.info = JobInfo(job_id=job_id, entrypoint=entrypoint,
                            metadata=metadata, runtime_env=runtime_env)
        self._logs: List[str] = []
        self._proc: Optional[subprocess.Popen] = None
        self._stop_requested = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        env = dict(os.environ)
        renv = self.info.runtime_env or {}
        env.update({str(k): str(v)
                    for k, v in (renv.get("env_vars") or {}).items()})
        # The attribution channel into the entrypoint: a driver process
        # started under this env tags every submission with the job id
        # (task_spec.default_job_id), so the job's tasks/metrics/objects
        # are attributable cluster-wide without code changes.
        env["RAY_TPU_JOB_ID"] = self.info.job_id
        cwd = renv.get("working_dir") or None
        self.info.status = JobStatus.RUNNING
        self.info.start_time = time.time()
        try:
            self._proc = subprocess.Popen(
                self.info.entrypoint, shell=True, cwd=cwd, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True)
            for line in self._proc.stdout:
                self._logs.append(line.rstrip("\n"))
            rc = self._proc.wait()
            self.info.return_code = rc
            if self._stop_requested:
                self.info.status = JobStatus.STOPPED
            elif rc == 0:
                self.info.status = JobStatus.SUCCEEDED
            else:
                self.info.status = JobStatus.FAILED
                self.info.message = f"entrypoint exited with code {rc}"
        except Exception as e:  # noqa: BLE001
            self.info.status = JobStatus.FAILED
            self.info.message = str(e)
        finally:
            self.info.end_time = time.time()

    def get_info(self) -> JobInfo:
        return self.info

    def get_logs(self) -> str:
        return "\n".join(self._logs)

    def stop(self) -> bool:
        self._stop_requested = True
        if self._proc and self._proc.poll() is None:
            self._proc.terminate()
        return True


class JobSubmissionClient:
    """Reference: `python/ray/job_submission/JobSubmissionClient` (the SDK
    normally speaks HTTP to the dashboard; in-process it drives the
    supervisors directly — same surface)."""

    def __init__(self, address: Optional[str] = None):
        self._jobs: Dict[str, Any] = {}
        ray_tpu.init(ignore_reinit_error=True)

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[dict] = None,
                   metadata: Optional[Dict[str, str]] = None,
                   submission_id: Optional[str] = None) -> str:
        job_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        if job_id in self._jobs:
            raise ValueError(f"job {job_id} already exists")
        # The supervisor actor (and anything it spawns in-process) is
        # part of the job it supervises: tag its creation so the job's
        # footprint starts at the supervisor, not at the first
        # entrypoint task.
        from ray_tpu._private.task_spec import set_ambient_job_id

        prev = set_ambient_job_id(job_id)
        try:
            supervisor = JobSupervisor.options(
                name=f"_job_supervisor:{job_id}", lifetime="detached",
                max_concurrency=4,
            ).remote(job_id, entrypoint, runtime_env, metadata or {})
        finally:
            set_ambient_job_id(prev)
        self._jobs[job_id] = supervisor
        return job_id

    def _supervisor(self, job_id: str):
        sup = self._jobs.get(job_id)
        if sup is None:
            sup = ray_tpu.get_actor(f"_job_supervisor:{job_id}")
            self._jobs[job_id] = sup
        return sup

    def get_job_status(self, job_id: str) -> str:
        return ray_tpu.get(
            self._supervisor(job_id).get_info.remote()).status

    def get_job_info(self, job_id: str) -> JobInfo:
        return ray_tpu.get(self._supervisor(job_id).get_info.remote())

    def get_job_logs(self, job_id: str) -> str:
        return ray_tpu.get(self._supervisor(job_id).get_logs.remote())

    def stop_job(self, job_id: str) -> bool:
        return ray_tpu.get(self._supervisor(job_id).stop.remote())

    def list_jobs(self) -> List[JobInfo]:
        # Discover supervisors from the named-actor registry, not the
        # client-local dict: any client (e.g. each REST request makes a
        # fresh one) must see every job in the cluster.
        from ray_tpu.experimental import state

        for row in state.list_actors():
            name = row.get("name") or ""
            if name.startswith("_job_supervisor:"):
                job_id = name[len("_job_supervisor:"):]
                if job_id not in self._jobs and row["state"] != "DEAD":
                    try:
                        self._jobs[job_id] = ray_tpu.get_actor(name)
                    except ValueError:
                        pass
        return [ray_tpu.get(s.get_info.remote())
                for s in self._jobs.values()]

    def wait_until_finish(self, job_id: str, timeout: float = 300.0,
                          poll: float = 0.2) -> JobInfo:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            info = self.get_job_info(job_id)
            if info.status in JobStatus.TERMINAL:
                return info
            time.sleep(poll)
        raise TimeoutError(f"job {job_id} not finished in {timeout}s")
