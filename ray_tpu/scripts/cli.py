"""CLI: cluster/job/observability commands.

Reference: `python/ray/scripts/scripts.py` (`ray start/stop/status/
memory/timeline/summary`, `ray job submit/...`). Run as
`python -m ray_tpu.scripts.cli <command>`.
"""

from __future__ import annotations

import argparse
import json
import sys


def cmd_status(args):
    import ray_tpu

    ray_tpu.init(ignore_reinit_error=True)
    print(json.dumps({
        "nodes": ray_tpu.nodes(),
        "cluster_resources": ray_tpu.cluster_resources(),
        "available_resources": ray_tpu.available_resources(),
    }, indent=2, default=str))


def cmd_summary(args):
    import ray_tpu
    from ray_tpu.experimental import state

    ray_tpu.init(ignore_reinit_error=True)
    kind = args.kind
    fn = {"tasks": state.summarize_tasks,
          "actors": state.summarize_actors,
          "objects": state.summarize_objects}[kind]
    print(json.dumps(fn(), indent=2, default=str))


def cmd_list(args):
    import ray_tpu
    from ray_tpu.experimental import state

    ray_tpu.init(ignore_reinit_error=True)
    fn = {"tasks": state.list_tasks, "actors": state.list_actors,
          "objects": state.list_objects,
          "nodes": state.list_nodes,
          "placement-groups": state.list_placement_groups}[args.kind]
    print(json.dumps(fn(), indent=2, default=str))


def cmd_timeline(args):
    import ray_tpu

    ray_tpu.init(ignore_reinit_error=True)
    out = args.output or "timeline.json"
    ray_tpu.timeline(out)
    print(f"wrote {out}")


def cmd_memory(args):
    import ray_tpu
    from ray_tpu.experimental import state

    ray_tpu.init(ignore_reinit_error=True)
    rows = state.list_objects()
    print(json.dumps({"objects": rows,
                      "summary": state.summarize_objects()},
                     indent=2, default=str))


def cmd_job(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    if args.job_cmd == "submit":
        job_id = client.submit_job(entrypoint=" ".join(args.entrypoint))
        if args.wait:
            info = client.wait_until_finish(job_id)
            print(client.get_job_logs(job_id))
            print(f"{job_id}: {info.status}")
            sys.exit(0 if info.status == "SUCCEEDED" else 1)
        print(job_id)
    elif args.job_cmd == "status":
        print(client.get_job_status(args.job_id))
    elif args.job_cmd == "logs":
        print(client.get_job_logs(args.job_id))
    elif args.job_cmd == "stop":
        client.stop_job(args.job_id)
        print("stopped")


def cmd_jobs(args):
    """Per-job attribution view: task counts by state, CPU-seconds,
    object-store footprint, and serve requests by route, per job tag
    (cluster-wide on a head)."""
    import ray_tpu
    from ray_tpu.experimental import state

    ray_tpu.init(ignore_reinit_error=True)
    summary = state.job_summary()
    if args.job_id:
        summary = {args.job_id: summary.get(args.job_id, {})}
    print(json.dumps(summary, indent=2, default=str))


def cmd_health(args):
    """Node + cluster health verdict (the /api/healthz payload). Exits
    nonzero when degraded so scripts can gate on it."""
    import ray_tpu
    from ray_tpu._private.health import evaluate_health

    ray_tpu.init(ignore_reinit_error=True)
    verdict = evaluate_health()
    print(json.dumps(verdict, indent=2, default=str))
    sys.exit(0 if verdict["status"] == "ok" else 1)


def cmd_slow(args):
    """Top-N slowest request waterfalls (the /api/slow_requests
    payload): per-request stage breakdown with the dominant stage
    named, so "where did the time go" is one command."""
    import ray_tpu
    from ray_tpu._private import critical_path

    ray_tpu.init(ignore_reinit_error=True)
    rows = critical_path.slow_requests(n=args.n)
    if args.json:
        print(json.dumps({
            "slow_requests": rows,
            "attribution": critical_path.attribution_vectors(),
        }, indent=2, default=str))
        return
    if not rows:
        print("no finished requests recorded")
        return
    for row in rows:
        print(f"{row['trace_id']}  route={row['route']} "
              f"status={row['status']} total={row['total_s'] * 1e3:.1f}ms "
              f"dominant={row['dominant_stage']}")
        for st in row["stages"]:
            bar = "#" * max(1, int(round(st.get("frac", 0.0) * 40)))
            print(f"    {st['stage']:<18} "
                  f"{st['dur_s'] * 1e3:9.2f}ms  {bar}")


def cmd_serve(args):
    """`serve deploy/run/status/shutdown` (reference
    `serve/scripts.py` CLI over the REST schema)."""
    import json

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve import schema

    ray_tpu.init(ignore_reinit_error=True)
    if args.serve_cmd == "deploy":
        import yaml

        with open(args.config_file) as f:
            config = yaml.safe_load(f)
        schema.apply_config(config)
        print(f"deployed {len(config.get('applications', []))} "
              "application(s)")
    elif args.serve_cmd == "run":
        # serve.run binds bare Deployments itself
        serve.run(schema.import_target(args.import_path),
                  route_prefix=args.route_prefix)
        print(f"serving {args.import_path}")
        if args.blocking:
            import time

            try:
                while True:
                    time.sleep(3600)
            except KeyboardInterrupt:
                pass
    elif args.serve_cmd == "status":
        print(json.dumps(schema.status_schema(), indent=2, default=str))
    elif args.serve_cmd == "shutdown":
        serve.shutdown()
        print("serve shut down")


def cmd_dashboard(args):
    from ray_tpu.dashboard import start_dashboard

    server = start_dashboard(port=args.port)
    print(f"dashboard at http://{server.host}:{server.port}")
    try:
        import time

        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass


def main(argv=None):
    parser = argparse.ArgumentParser("ray_tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    sub.add_parser("status").set_defaults(fn=cmd_status)

    p = sub.add_parser("summary")
    p.add_argument("kind", choices=["tasks", "actors", "objects"])
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser("list")
    p.add_argument("kind", choices=["tasks", "actors", "objects", "nodes",
                                    "placement-groups"])
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("timeline")
    p.add_argument("-o", "--output", default=None)
    p.set_defaults(fn=cmd_timeline)

    sub.add_parser("memory").set_defaults(fn=cmd_memory)

    p = sub.add_parser("jobs")
    p.add_argument("job_id", nargs="?", default=None)
    p.set_defaults(fn=cmd_jobs)

    sub.add_parser("health").set_defaults(fn=cmd_health)

    p = sub.add_parser("slow")
    p.add_argument("-n", type=int, default=10)
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_slow)

    p = sub.add_parser("job")
    jsub = p.add_subparsers(dest="job_cmd", required=True)
    ps = jsub.add_parser("submit")
    ps.add_argument("--wait", action="store_true")
    ps.add_argument("entrypoint", nargs=argparse.REMAINDER)
    for name in ("status", "logs", "stop"):
        pj = jsub.add_parser(name)
        pj.add_argument("job_id")
    p.set_defaults(fn=cmd_job)

    p = sub.add_parser("dashboard")
    p.add_argument("--port", type=int, default=8265)
    p.set_defaults(fn=cmd_dashboard)

    p = sub.add_parser("serve")
    ssub = p.add_subparsers(dest="serve_cmd", required=True)
    pd = ssub.add_parser("deploy")
    pd.add_argument("config_file")
    pr = ssub.add_parser("run")
    pr.add_argument("import_path")
    pr.add_argument("--route-prefix", default=None)
    pr.add_argument("--blocking", action="store_true")
    ssub.add_parser("status")
    ssub.add_parser("shutdown")
    p.set_defaults(fn=cmd_serve)

    args = parser.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
