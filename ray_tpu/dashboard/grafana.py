"""Grafana dashboard generation.

Reference: `dashboard/modules/metrics/grafana_dashboard_factory.py` —
emits importable Grafana dashboard JSON whose panels query the same
Prometheus metrics the framework exports (`util/metrics.py` text
exposition), so `ray_tpu metrics → Prometheus scrape → Grafana` works
out of the box without hand-building panels.

`generate_default_dashboard()` builds the core-runtime dashboard
(tasks/actors/objects/shm); `generate_dashboard(panels)` builds one for
arbitrary registered metrics. `write_dashboards(dir)` drops the JSON
files a Grafana provisioning directory expects.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Tuple

_PANEL_W, _PANEL_H = 12, 8


def _panel(panel_id: int, title: str, exprs: List[Tuple[str, str]], *,
           unit: str = "short", x: int = 0, y: int = 0) -> dict:
    return {
        "id": panel_id,
        "title": title,
        "type": "timeseries",
        "datasource": {"type": "prometheus",
                       "uid": "${datasource}"},
        "gridPos": {"h": _PANEL_H, "w": _PANEL_W, "x": x, "y": y},
        "fieldConfig": {"defaults": {"unit": unit}, "overrides": []},
        "targets": [
            {"expr": expr, "legendFormat": legend, "refId": chr(65 + i)}
            for i, (expr, legend) in enumerate(exprs)
        ],
    }


def generate_dashboard(title: str,
                       panels_spec: List[dict],
                       uid: Optional[str] = None) -> dict:
    """panels_spec: [{"title", "exprs": [(promql, legend)], "unit"?}]."""
    panels = []
    for i, spec in enumerate(panels_spec):
        panels.append(_panel(
            i + 1, spec["title"], spec["exprs"],
            unit=spec.get("unit", "short"),
            x=(i % 2) * _PANEL_W, y=(i // 2) * _PANEL_H))
    return {
        "uid": uid or title.lower().replace(" ", "-")[:40],
        "title": title,
        "timezone": "browser",
        "schemaVersion": 39,
        "refresh": "10s",
        "time": {"from": "now-30m", "to": "now"},
        "templating": {"list": [{
            "name": "datasource",
            "type": "datasource",
            "query": "prometheus",
        }]},
        "panels": panels,
    }


def generate_default_dashboard() -> dict:
    """The core-runtime dashboard over the canonical metrics
    (`_private/runtime_metrics.py` — the metric_defs.cc role)."""
    return generate_dashboard("ray_tpu core", [
        {"title": "Tasks by state",
         "exprs": [('sum(ray_tpu_tasks) by (state)', "{{state}}")]},
        {"title": "Actors by state",
         "exprs": [('sum(ray_tpu_actors) by (state)', "{{state}}")]},
        {"title": "Object store",
         "exprs": [("ray_tpu_object_store_objects", "objects"),
                   ("ray_tpu_object_store_spilled_objects", "spilled")]},
        {"title": "Object store bytes", "unit": "bytes",
         "exprs": [("ray_tpu_object_store_bytes", "bytes")]},
        {"title": "Shared-memory segment", "unit": "bytes",
         "exprs": [("ray_tpu_shm_allocated", "allocated"),
                   ("ray_tpu_shm_capacity", "capacity")]},
        {"title": "Cluster resources",
         "exprs": [('sum(ray_tpu_resources_available) by (resource)',
                    "available {{resource}}"),
                   ('sum(ray_tpu_resources_total) by (resource)',
                    "total {{resource}}")]},
    ], uid="ray-tpu-core")


def generate_serve_dashboard() -> dict:
    return generate_dashboard("ray_tpu serve", [
        {"title": "Deployment replicas",
         "exprs": [('sum(ray_tpu_serve_replicas) by (deployment)',
                    "{{deployment}}")]},
        {"title": "Handle queue depth",
         "exprs": [('sum(ray_tpu_serve_queued) by (deployment)',
                    "{{deployment}}")]},
        {"title": "HTTP route latency", "unit": "s",
         "exprs": [('ray_tpu_serve_request_seconds_p50', "p50 {{route}}"),
                   ('ray_tpu_serve_request_seconds_p95',
                    "p95 {{route}}")]},
        {"title": "HTTP ingress",
         "exprs": [("ray_tpu_serve_http_in_flight", "in flight"),
                   ("ray_tpu_serve_http_open_connections", "connections"),
                   ("ray_tpu_serve_http_shed_503", "shed (503)")]},
        {"title": "Replica latency", "unit": "s",
         "exprs": [('ray_tpu_serve_replica_request_seconds_p95',
                    "p95 {{deployment}} {{node}}")]},
        # -- LLM serving row (PR 16): TTFT + the prefix/KV cache -------
        {"title": "LLM TTFT", "unit": "s",
         "exprs": [('ray_tpu_serve_ttft_seconds_p50',
                    "p50 {{route}} {{model}}"),
                   ('ray_tpu_serve_ttft_seconds_p99',
                    "p99 {{route}} {{model}}")]},
        {"title": "LLM KV cache",
         "exprs": [("rate(ray_tpu_llm_kv_cache_hits[1m])", "hits/s"),
                   ("rate(ray_tpu_llm_kv_cache_misses[1m])",
                    "misses/s"),
                   ("rate(ray_tpu_llm_kv_cache_evictions[1m])",
                    "evictions/s")]},
        {"title": "LLM KV cache bytes", "unit": "bytes",
         "exprs": [("ray_tpu_llm_kv_cache_bytes", "resident"),
                   ("rate(ray_tpu_llm_kv_shm_offloads[5m])",
                    "shm offloads/s"),
                   ("rate(ray_tpu_llm_kv_shm_restores[5m])",
                    "shm restores/s")]},
        {"title": "LLM model multiplexing",
         "exprs": [("increase(ray_tpu_llm_model_swaps[5m])",
                    "swaps (5m)"),
                   ("increase(ray_tpu_serve_affinity_routed[5m])",
                    "affinity-routed {{placed}} (5m)")]},
        # -- Request anatomy row (PR 18): the critical-path engine's
        # per-(route, stage) attribution vectors. The p99 panel is the
        # jump-off to /api/slow_requests, whose exemplar trace-ids name
        # the trace behind each slow bucket.
        {"title": "Request anatomy p50 (stacked by stage)", "unit": "s",
         "exprs": [('sum(ray_tpu_request_stage_seconds_p50) '
                    'by (route, stage)', "{{route}} {{stage}}")]},
        {"title": "Request anatomy p99 (exemplars: /api/slow_requests)",
         "unit": "s",
         "exprs": [('sum(ray_tpu_request_stage_seconds_p99) '
                    'by (route, stage)', "{{route}} {{stage}}")]},
        {"title": "Affinity hit rate",
         "exprs": [("rate(ray_tpu_serve_affinity_hits_total[1m]) / "
                    "(rate(ray_tpu_serve_affinity_hits_total[1m]) + "
                    "rate(ray_tpu_serve_affinity_misses_total[1m]))",
                    "hit rate"),
                   ("rate(ray_tpu_serve_affinity_misses_total[1m])",
                    "misses/s")]},
    ], uid="ray-tpu-serve")


def generate_observability_dashboard() -> dict:
    """Fast-path + shipping-plane panels over the node-tagged series the
    head's merged /api/metrics exposes (`_private/perf_stats.py` via
    `runtime_metrics`)."""
    return generate_dashboard("ray_tpu observability", [
        {"title": "Batcher queue delay", "unit": "s",
         "exprs": [("ray_tpu_batcher_queue_delay_seconds_p50",
                    "p50 {{node}}"),
                   ("ray_tpu_batcher_queue_delay_seconds_p95",
                    "p95 {{node}}")]},
        {"title": "Batcher flush size",
         "exprs": [("ray_tpu_batcher_flush_items_p50", "p50 {{node}}"),
                   ("ray_tpu_batcher_flush_items_p95", "p95 {{node}}")]},
        {"title": "Submit→start latency", "unit": "s",
         "exprs": [("ray_tpu_sched_submit_to_start_seconds_p50",
                    "p50 {{node}}"),
                   ("ray_tpu_sched_submit_to_start_seconds_p95",
                    "p95 {{node}}")]},
        {"title": "Template intern hit rate",
         "exprs": [("rate(ray_tpu_intern_hits_total[1m]) / "
                    "(rate(ray_tpu_intern_hits_total[1m]) + "
                    "rate(ray_tpu_intern_misses_total[1m]))",
                    "hit rate {{node}}")]},
        {"title": "GCS group-commit", "unit": "s",
         "exprs": [("ray_tpu_gcs_commit_seconds_p95", "p95"),
                   ("rate(ray_tpu_gcs_writes_total[1m])",
                    "writes/s")]},
        {"title": "Wait path",
         "exprs": [("rate(ray_tpu_wait_calls_total[1m])", "calls/s"),
                   ("rate(ray_tpu_wait_snapshot_hits_total[1m])",
                    "snapshot hits/s"),
                   ("rate(ray_tpu_wait_wakeups_total[1m])",
                    "wake-ups/s")]},
        {"title": "Event shipping",
         "exprs": [("rate(ray_tpu_obs_shipped_events_total[1m])",
                    "events/s {{node}}"),
                   ("rate(ray_tpu_obs_ship_cycles_total[1m])",
                    "cycles/s {{node}}")]},
        # -- head shards row (PR 19): the multi-process control plane --
        {"title": "Head shard RPC frames",
         "exprs": [("rate(ray_tpu_head_shard_rpcs_total[1m])",
                    "frames/s shard {{shard}}")]},
        {"title": "Head shard stream backlog",
         "exprs": [("ray_tpu_head_shard_queue_depth_p95",
                    "p95 shard {{shard}}")]},
        {"title": "Head shard group-commit", "unit": "s",
         "exprs": [("ray_tpu_head_shard_commit_seconds_p95",
                    "p95 shard {{shard}}"),
                   ("ray_tpu_head_shard_commit_seconds_p50",
                    "p50 shard {{shard}}")]},
    ], uid="ray-tpu-observability")


def generate_jobs_dashboard() -> dict:
    """Per-job (tenant) attribution + SLO/health panels over the
    job-tagged series (`_private/runtime_metrics._collect_job_metrics`,
    the ingress `serve_requests{job,route}` counter) and the health
    plane's burn/lag/pressure gauges (`_private/health.py`)."""
    return generate_dashboard("ray_tpu jobs", [
        {"title": "Top jobs by CPU-seconds", "unit": "s",
         "exprs": [('topk(10, sum(ray_tpu_job_cpu_seconds) by (job))',
                    "{{job}}")]},
        {"title": "Tasks by job",
         "exprs": [('sum(ray_tpu_job_tasks) by (job, state)',
                    "{{job}} {{state}}")]},
        {"title": "Object-store bytes by job", "unit": "bytes",
         "exprs": [('sum(ray_tpu_job_object_store_bytes) by (job)',
                    "{{job}}")]},
        {"title": "Serve requests by job",
         "exprs": [('sum(rate(ray_tpu_serve_requests_total[1m])) '
                    'by (job, route)', "{{job}} {{route}}")]},
        {"title": "Serve SLO burn rate",
         "exprs": [('ray_tpu_serve_slo_burn_rate',
                    "{{route}} {{window}}")]},
        {"title": "Overload signals",
         "exprs": [("ray_tpu_event_loop_lag_last_seconds",
                    "loop lag {{component}} {{node}}"),
                   ("ray_tpu_memory_pressure",
                    "memory pressure {{node}}"),
                   ("ray_tpu_sched_backlog", "backlog {{node}}")]},
    ], uid="ray-tpu-jobs")


def generate_object_plane_dashboard() -> dict:
    """Object-plane bandwidth panels (the PR 10 overhaul): shm probe
    hit rate, native pull volume/latency, spill/restore traffic, arena
    occupancy + eviction/backpressure pressure signals — all node-
    tagged through the head's merged exposition."""
    return generate_dashboard("ray_tpu object plane", [
        {"title": "Shm probe hit rate",
         "exprs": [("rate(ray_tpu_object_shm_hit_total[1m]) / "
                    "(rate(ray_tpu_object_shm_hit_total[1m]) + "
                    "rate(ray_tpu_object_shm_miss_total[1m]))",
                    "hit rate {{node}}")]},
        {"title": "Native pull throughput", "unit": "Bps",
         "exprs": [("rate(ray_tpu_object_pull_bytes_total[1m])",
                    "pull B/s {{node}}")]},
        {"title": "Pull latency", "unit": "s",
         "exprs": [("ray_tpu_object_pull_seconds_p50", "p50 {{node}}"),
                   ("ray_tpu_object_pull_seconds_p95",
                    "p95 {{node}}")]},
        {"title": "Pull slot wait", "unit": "s",
         "exprs": [("ray_tpu_object_pull_slot_wait_seconds_p95",
                    "p95 {{node}}")]},
        {"title": "Spill / restore", "unit": "Bps",
         "exprs": [("rate(ray_tpu_object_spill_bytes_total[1m])",
                    "spill B/s {{node}}"),
                   ("rate(ray_tpu_object_restore_bytes_total[1m])",
                    "restore B/s {{node}}")]},
        {"title": "Arena pressure",
         "exprs": [("rate(ray_tpu_shm_evictions[1m])",
                    "evictions/s {{node}}"),
                   ("rate(ray_tpu_object_create_backpressure_waits_"
                    "total[1m])", "backpressure waits/s {{node}}"),
                   ("rate(ray_tpu_object_shm_spills_total[1m])",
                    "arena spills/s {{node}}")]},
        {"title": "Arena occupancy", "unit": "bytes",
         "exprs": [("ray_tpu_shm_allocated", "allocated {{node}}"),
                   ("ray_tpu_shm_capacity", "capacity {{node}}")]},
        # Fault-tolerance row: what the recovery machinery is doing —
        # node deaths + the bytes they took, lineage reconstructions by
        # outcome (reexecute / from_spill / exhausted), and actor
        # restarts by outcome (restarted / exhausted / call_replayed /
        # call_rejected).
        {"title": "Node deaths / lost bytes",
         "exprs": [("increase(ray_tpu_node_deaths_total[5m])",
                    "deaths (5m)"),
                   ("increase(ray_tpu_node_death_lost_bytes_total[5m])",
                    "lost bytes (5m)")]},
        {"title": "Reconstructions by outcome",
         "exprs": [("increase(ray_tpu_reconstructions_total[5m])",
                    "{{outcome}} (5m)")]},
        {"title": "Actor restarts / call replay-or-reject",
         "exprs": [("increase(ray_tpu_actor_restarts_total[5m])",
                    "{{outcome}} (5m)")]},
    ], uid="ray-tpu-object-plane")


def generate_tenancy_dashboard() -> dict:
    """Tenancy ENFORCEMENT panels (the other half of the jobs
    dashboard's attribution view): what the quota/WFQ/rate-limit/
    arena-budget machinery is actively doing to each tenant —
    `_private/tenancy.py` counters + live ledger gauges."""
    return generate_dashboard("ray_tpu tenancy", [
        {"title": "Quota rejections / parks",
         "exprs": [("increase(ray_tpu_job_quota_rejections_total[5m])",
                    "rejected {{job}} (5m)"),
                   ("increase(ray_tpu_job_quota_parks_total[5m])",
                    "parked {{job}} (5m)"),
                   ("increase(ray_tpu_job_quota_lease_denials_total"
                    "[5m])", "lease denials {{job}} (5m)")]},
        {"title": "CPU-slot usage vs quota",
         "exprs": [('sum(ray_tpu_job_quota_cpu_milli) by (job)',
                    "running milli-CPU {{job}}")]},
        {"title": "Queued / parked behind own limit",
         "exprs": [('sum(ray_tpu_job_quota_queued) by (job)',
                    "queued {{job}}"),
                   ('sum(ray_tpu_job_quota_parked) by (job)',
                    "parked {{job}}")]},
        {"title": "Ingress rate limiting",
         "exprs": [("increase(ray_tpu_job_rate_limited_total[5m])",
                    "429s {{job}} (5m)"),
                   ("increase(ray_tpu_serve_http_limited_429[5m])",
                    "429s total (5m)"),
                   ("increase(ray_tpu_serve_http_denied_401[5m])",
                    "401s total (5m)")]},
        {"title": "Arena bytes by job vs budget", "unit": "bytes",
         "exprs": [('sum(ray_tpu_job_arena_bytes) by (job)',
                    "{{job}}")]},
        {"title": "Arena budget spills", "unit": "Bps",
         "exprs": [("rate(ray_tpu_job_arena_spill_bytes_total[1m])",
                    "spill B/s {{job}}")]},
    ], uid="ray-tpu-tenancy")


def write_dashboards(directory: str) -> List[str]:
    """Write all generated dashboards into a Grafana provisioning dir;
    returns the file paths."""
    os.makedirs(directory, exist_ok=True)
    out = []
    for dash in (generate_default_dashboard(),
                 generate_serve_dashboard(),
                 generate_observability_dashboard(),
                 generate_jobs_dashboard(),
                 generate_object_plane_dashboard(),
                 generate_tenancy_dashboard()):
        path = os.path.join(directory, f"{dash['uid']}.json")
        with open(path, "w") as f:
            json.dump(dash, f, indent=2)
        out.append(path)
    return out
