"""Dashboard: HTTP JSON API over cluster state.

Reference: `dashboard/` (head + modules; SURVEY.md §2.2). The API surface
(nodes/tasks/actors/objects/jobs/metrics/serve) is served by a threaded
stdlib HTTP server reading the state API, metrics registry, and serve
controller — the aggregation role of `dashboard/state_aggregator.py`.
The React UI is out of scope; the JSON API is the contract.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class DashboardServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        dashboard = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                try:
                    body, ctype = dashboard._route(self.path)
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                    self.end_headers()
                    self.wfile.write(body)
                except KeyError:
                    self.send_response(404)
                    self.end_headers()
                except Exception as e:  # noqa: BLE001
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(str(e).encode())

            def do_POST(self):
                # Job submission REST (reference: dashboard job_head —
                # POST /api/jobs/ {entrypoint, metadata?, runtime_env?};
                # POST /api/jobs/<id>/stop).
                path = self.path.split("?")[0].rstrip("/")
                try:
                    from ray_tpu.job_submission import JobSubmissionClient

                    client = JobSubmissionClient()
                    if path == "/api/jobs":
                        n = int(self.headers.get("Content-Length", 0))
                        spec = json.loads(self.rfile.read(n) or b"{}")
                        if "entrypoint" not in spec:
                            raise ValueError("job spec requires "
                                             "'entrypoint'")
                        job_id = client.submit_job(
                            entrypoint=spec["entrypoint"],
                            metadata=spec.get("metadata"),
                            runtime_env=spec.get("runtime_env"))
                        self._json(200, {"job_id": job_id})
                    elif path.startswith("/api/jobs/") and \
                            path.endswith("/stop"):
                        job_id = path[len("/api/jobs/"):-len("/stop")]
                        self._json(200,
                                   {"stopped": client.stop_job(job_id)})
                    else:
                        self.send_response(404)
                        self.end_headers()
                except ValueError as e:
                    self.send_response(400)
                    self.end_headers()
                    self.wfile.write(str(e).encode())
                except Exception as e:  # noqa: BLE001
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(str(e).encode())

            def _json(self, code, obj):
                body = json.dumps(obj, default=str).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(body)

            def do_PUT(self):
                # Declarative serve deploy (reference REST:
                # PUT /api/serve/applications/ with a ServeDeploySchema
                # JSON body).
                path = self.path.split("?")[0].rstrip("/")
                if path != "/api/serve/applications":
                    self.send_response(404)
                    self.end_headers()
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    config = json.loads(self.rfile.read(n) or b"{}")
                    from ray_tpu.serve.schema import apply_config

                    apply_config(config)
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.end_headers()
                    self.wfile.write(b'{"status": "ok"}')
                except ValueError as e:
                    self.send_response(400)
                    self.end_headers()
                    self.wfile.write(str(e).encode())
                except Exception as e:  # noqa: BLE001
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(str(e).encode())

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="dashboard")
        self._thread.start()

    def _route(self, path: str):
        import ray_tpu
        from ray_tpu.experimental import state

        path = path.split("?")[0].rstrip("/") or "/"
        if path == "/api/metrics":
            # CLUSTER-wide exposition: the head's registry (canonical
            # runtime gauges refreshed first, per runtime_metrics'
            # contract) merged with every node's shipped snapshot,
            # node series tagged node="<id>". Content-Type per the
            # Prometheus text exposition spec (0.0.4).
            from ray_tpu._private.obs_plane import (
                export_cluster_prometheus,
            )
            from ray_tpu._private.worker import global_worker
            from ray_tpu.util.metrics import PROMETHEUS_CONTENT_TYPE

            return (export_cluster_prometheus(global_worker()).encode(),
                    PROMETHEUS_CONTENT_TYPE)
        if path == "/api/traces":
            # OTLP-shaped span export (cluster-wide on a head): the
            # resourceSpans/scopeSpans envelope any OpenTelemetry
            # backend expects, spans from experimental.tracing.
            from ray_tpu.experimental.tracing import export_spans

            body = json.dumps({"resourceSpans": [{
                "resource": {"attributes": [
                    {"key": "service.name",
                     "value": {"stringValue": "ray_tpu"}}]},
                "scopeSpans": [{
                    "scope": {"name": "ray_tpu.experimental.tracing"},
                    "spans": export_spans(),
                }],
            }]}, default=str).encode()
            return body, "application/json"
        if path == "/api/healthz":
            # Node + cluster overload verdict (SLO burn, event-loop
            # lag, scheduler backlog, memory pressure) with reasons
            # naming the overloaded signal — the load-shedding /
            # autoscaling signal surface. Always 200; readers key off
            # the "status" field ("ok" | "degraded").
            from ray_tpu._private.health import evaluate_health

            return (json.dumps(evaluate_health(), default=str).encode(),
                    "application/json")
        if path == "/api/slow_requests":
            # Critical-path attribution: top-N slowest finished request
            # waterfalls (dominant stage named per request), per-route
            # p50/p99 stage-attribution vectors, and the exemplar
            # trace-ids pinned to the slowest histogram buckets.
            from ray_tpu._private import critical_path

            return (json.dumps({
                "slow_requests": critical_path.slow_requests(),
                "attribution": critical_path.attribution_vectors(),
                "exemplars": critical_path.exemplars(),
            }, default=str).encode(), "application/json")
        if path == "/api/debug/dump":
            # On-demand flight dump: every live node ships its bounded
            # span/sample rings to the head; the correlated payload is
            # returned inline and — when flight_recorder_dir is set —
            # also written as FLIGHT_<ts>.json (the "path" key).
            from ray_tpu._private import flight_recorder, health
            from ray_tpu._private.worker import global_worker_or_none

            w = global_worker_or_none()
            payload = flight_recorder.dump(
                "api", worker=w, verdict=health.evaluate_health(w))
            return (json.dumps(payload, default=str).encode(),
                    "application/json")
        if path == "/ui":
            return _UI_HTML.encode(), "text/html"
        if path == "/api/jobs" or path.startswith("/api/jobs/"):
            return (json.dumps(self._jobs_route(path),
                               default=str).encode(), "application/json")
        if path == "/api/logs" or path.startswith("/api/logs/"):
            return (json.dumps(self._logs_route(path),
                               default=str).encode(), "application/json")
        if path == "/api/events":
            from ray_tpu._private.events import list_events

            return (json.dumps(list_events(), default=str).encode(),
                    "application/json")
        routes = {
            "/": lambda: {"status": "ok",
                          "endpoints": ["/ui", "/api/nodes", "/api/tasks",
                                        "/api/actors", "/api/objects",
                                        "/api/cluster_status",
                                        "/api/serve", "/api/metrics",
                                        "/api/traces", "/api/timeline",
                                        "/api/logs", "/api/events",
                                        "/api/healthz",
                                        "/api/slow_requests",
                                        "/api/debug/dump",
                                        "/api/job_summary"]},
            "/api/nodes": state.list_nodes,
            "/api/tasks": state.list_tasks,
            "/api/actors": state.list_actors,
            "/api/objects": state.list_objects,
            "/api/placement_groups": state.list_placement_groups,
            "/api/timeline": ray_tpu.timeline,
            # Per-job resource accounting (tasks by state, CPU-seconds,
            # object-store footprint, serve requests by route).
            "/api/job_summary": state.job_summary,
            "/api/cluster_status": lambda: {
                "cluster_resources": ray_tpu.cluster_resources(),
                "available_resources": ray_tpu.available_resources(),
                "task_summary": state.summarize_tasks(),
                "actor_summary": state.summarize_actors(),
                # Per-handler control-plane latency (the reference's
                # instrumented_io_context event-stats role).
                "head_rpc_handlers": self._head_handler_stats(),
            },
            "/api/serve": self._serve_status,
            "/api/serve/applications": self._serve_applications,
        }
        fn = routes[path]  # KeyError → 404
        return json.dumps(fn(), default=str).encode(), "application/json"

    @staticmethod
    def _jobs_route(path: str):
        import dataclasses

        from ray_tpu.job_submission import JobSubmissionClient

        client = JobSubmissionClient()
        if path == "/api/jobs":
            return [dataclasses.asdict(j) for j in client.list_jobs()]
        rest = path[len("/api/jobs/"):]
        if rest.endswith("/logs"):
            return {"logs": client.get_job_logs(rest[:-len("/logs")])}
        return dataclasses.asdict(client.get_job_info(rest))

    @staticmethod
    def _head_handler_stats():
        from ray_tpu._private.worker import global_worker_or_none

        worker = global_worker_or_none()
        head = getattr(worker, "cluster_head", None) if worker else None
        server = getattr(head, "server", None)
        return server.handler_stats() if server is not None else {}

    @staticmethod
    def _logs_route(path: str):
        """Per-node log files (reference: dashboard log module).
        /api/logs lists nodes; /api/logs/<node_id>?  tails 16 KB."""
        import os

        from ray_tpu._private.worker import global_worker_or_none

        worker = global_worker_or_none()
        head = getattr(worker, "cluster_head", None) if worker else None
        logs = dict(getattr(head, "node_logs", {}) or {})
        if path == "/api/logs":
            return {nid: {"path": p,
                          "size": os.path.getsize(p)
                          if os.path.exists(p) else 0}
                    for nid, p in logs.items()}
        node_id = path[len("/api/logs/"):]
        p = logs.get(node_id)
        if p is None or not os.path.exists(p):
            return {"error": f"no log for node {node_id!r}"}
        size = os.path.getsize(p)
        with open(p, "rb") as f:
            f.seek(max(0, size - (16 << 10)))
            tail = f.read().decode("utf-8", "replace")
        return {"node_id": node_id, "path": p, "size": size,
                "tail": tail}

    @staticmethod
    def _serve_status():
        try:
            from ray_tpu import serve

            return serve.status()
        except Exception:
            return {}

    @staticmethod
    def _serve_applications():
        try:
            from ray_tpu.serve.schema import status_schema

            return status_schema()
        except Exception:
            return {}

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()


# Minimal single-file UI over the JSON API (the reference ships a React
# app, `dashboard/client/`; the JSON API remains the contract — this
# page is a zero-dependency reader for humans).
_UI_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>ray_tpu dashboard</title>
<style>
 body{font-family:system-ui,sans-serif;margin:1.5rem;background:#fafafa}
 h1{font-size:1.3rem} h2{font-size:1.05rem;margin:1.2em 0 .4em}
 table{border-collapse:collapse;background:#fff;font-size:.85rem}
 th,td{border:1px solid #ddd;padding:.3em .6em;text-align:left}
 th{background:#f0f0f0} .num{text-align:right}
 #err{color:#b00} .muted{color:#777}
</style></head><body>
<h1>ray_tpu dashboard <span id="ts" class="muted"></span></h1>
<div id="err"></div>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Cluster resources</h2><table id="res"></table>
<h2>Task summary</h2><table id="tasks"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Serve applications</h2><table id="serve"></table>
<script>
const fmt = (b) => b==null ? "" :
  b > 1e9 ? (b/1e9).toFixed(1)+" GB" :
  b > 1e6 ? (b/1e6).toFixed(1)+" MB" : b;
function table(el, rows, cols){
  el.innerHTML = "<tr>"+cols.map(c=>"<th>"+c+"</th>").join("")+"</tr>" +
    rows.map(r=>"<tr>"+cols.map(c=>"<td>"+(r[c]??"")+"</td>").join("")
    +"</tr>").join("");
}
async function refresh(){
  try {
    const [nodes, status, actors, serve] = await Promise.all([
      fetch("/api/nodes").then(r=>r.json()),
      fetch("/api/cluster_status").then(r=>r.json()),
      fetch("/api/actors").then(r=>r.json()),
      fetch("/api/serve").then(r=>r.json())]);
    table(document.getElementById("nodes"), nodes.map(n=>({
      NodeID:(n.NodeID||"").slice(0,12), Alive:n.Alive,
      CPU:(n.Resources||{}).CPU, TPU:(n.Resources||{}).TPU||"",
      "cpu%":(n.Stats||{}).cpu_percent??"",
      "mem%":(n.Stats||{}).mem_percent??"",
      mem:fmt((n.Stats||{}).mem_total),
      pids:(n.Stats||{}).pid_count??""})),
      ["NodeID","Alive","CPU","TPU","cpu%","mem%","mem","pids"]);
    const res = status.cluster_resources||{},
          avail = status.available_resources||{};
    table(document.getElementById("res"),
      Object.keys(res).map(k=>({resource:k, total:res[k],
                                available:avail[k]??""})),
      ["resource","total","available"]);
    const ts = status.task_summary||{};
    table(document.getElementById("tasks"),
      Object.keys(ts).map(k=>({name:k,
        states:JSON.stringify(ts[k].states),
        "time (s)":ts[k].total_time_s})),
      ["name","states","time (s)"]);
    table(document.getElementById("actors"),
      (Array.isArray(actors)?actors:[]).map(a=>({
        actor_id:(a.actor_id||"").slice(0,12), class:a.class_name,
        state:a.state, name:a.name||""})),
      ["actor_id","class","state","name"]);
    table(document.getElementById("serve"),
      Object.entries(serve).map(([k,v])=>({deployment:k,
        status:(v||{}).status, replicas:(v||{}).num_replicas})),
      ["deployment","status","replicas"]);
    document.getElementById("ts").textContent =
      "refreshed " + new Date().toLocaleTimeString();
    document.getElementById("err").textContent = "";
  } catch (e) { document.getElementById("err").textContent = e; }
}
refresh(); setInterval(refresh, 5000);
</script></body></html>
"""

_server: Optional[DashboardServer] = None


def start_dashboard(host: str = "127.0.0.1",
                    port: int = 0) -> DashboardServer:
    global _server
    if _server is None:
        import ray_tpu

        ray_tpu.init(ignore_reinit_error=True)
        _server = DashboardServer(host, port)
    return _server


def shutdown_dashboard():
    global _server
    if _server is not None:
        _server.shutdown()
        _server = None
