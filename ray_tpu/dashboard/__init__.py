"""Dashboard: HTTP JSON API over cluster state.

Reference: `dashboard/` (head + modules; SURVEY.md §2.2). The API surface
(nodes/tasks/actors/objects/jobs/metrics/serve) is served by a threaded
stdlib HTTP server reading the state API, metrics registry, and serve
controller — the aggregation role of `dashboard/state_aggregator.py`.
The React UI is out of scope; the JSON API is the contract.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class DashboardServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        dashboard = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                try:
                    body, ctype = dashboard._route(self.path)
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                    self.end_headers()
                    self.wfile.write(body)
                except KeyError:
                    self.send_response(404)
                    self.end_headers()
                except Exception as e:  # noqa: BLE001
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(str(e).encode())

            def do_PUT(self):
                # Declarative serve deploy (reference REST:
                # PUT /api/serve/applications/ with a ServeDeploySchema
                # JSON body).
                path = self.path.split("?")[0].rstrip("/")
                if path != "/api/serve/applications":
                    self.send_response(404)
                    self.end_headers()
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    config = json.loads(self.rfile.read(n) or b"{}")
                    from ray_tpu.serve.schema import apply_config

                    apply_config(config)
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.end_headers()
                    self.wfile.write(b'{"status": "ok"}')
                except ValueError as e:
                    self.send_response(400)
                    self.end_headers()
                    self.wfile.write(str(e).encode())
                except Exception as e:  # noqa: BLE001
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(str(e).encode())

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="dashboard")
        self._thread.start()

    def _route(self, path: str):
        import ray_tpu
        from ray_tpu.experimental import state

        path = path.split("?")[0].rstrip("/") or "/"
        if path == "/api/metrics":
            from ray_tpu.util.metrics import export_prometheus

            return export_prometheus().encode(), "text/plain"
        routes = {
            "/": lambda: {"status": "ok",
                          "endpoints": ["/api/nodes", "/api/tasks",
                                        "/api/actors", "/api/objects",
                                        "/api/cluster_status",
                                        "/api/serve", "/api/metrics",
                                        "/api/timeline"]},
            "/api/nodes": state.list_nodes,
            "/api/tasks": state.list_tasks,
            "/api/actors": state.list_actors,
            "/api/objects": state.list_objects,
            "/api/placement_groups": state.list_placement_groups,
            "/api/timeline": ray_tpu.timeline,
            "/api/cluster_status": lambda: {
                "cluster_resources": ray_tpu.cluster_resources(),
                "available_resources": ray_tpu.available_resources(),
                "task_summary": state.summarize_tasks(),
                "actor_summary": state.summarize_actors(),
            },
            "/api/serve": self._serve_status,
            "/api/serve/applications": self._serve_applications,
        }
        fn = routes[path]  # KeyError → 404
        return json.dumps(fn(), default=str).encode(), "application/json"

    @staticmethod
    def _serve_status():
        try:
            from ray_tpu import serve

            return serve.status()
        except Exception:
            return {}

    @staticmethod
    def _serve_applications():
        try:
            from ray_tpu.serve.schema import status_schema

            return status_schema()
        except Exception:
            return {}

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()


_server: Optional[DashboardServer] = None


def start_dashboard(host: str = "127.0.0.1",
                    port: int = 0) -> DashboardServer:
    global _server
    if _server is None:
        import ray_tpu

        ray_tpu.init(ignore_reinit_error=True)
        _server = DashboardServer(host, port)
    return _server


def shutdown_dashboard():
    global _server
    if _server is not None:
        _server.shutdown()
        _server = None
