"""ray_tpu: a TPU-native distributed AI framework.

The public surface mirrors the reference's core API (``ray.init / remote /
get / put / wait`` + actors + placement groups) while the ML layers
(``ray_tpu.data/train/tune/serve/rl``) are built TPU-first on JAX/XLA.
"""

__version__ = "0.1.0"

import os as _os

# pyarrow's bundled jemalloc/mimalloc pool segfaults under this runtime's
# thread pattern (task threads building tables concurrently with consumer
# threads converting them — reproducibly crashed in combine_chunks). The
# glibc allocator is safe; must be set before the first pyarrow import
# anywhere in the process.
_os.environ.setdefault("ARROW_DEFAULT_MEMORY_POOL", "system")

from ray_tpu import exceptions  # noqa: F401
from ray_tpu._private.worker import (  # noqa: F401
    cancel,
    get,
    init,
    is_initialized,
    kill,
    put,
    shutdown,
    wait,
)
from ray_tpu._private.ray_client import (  # noqa: F401
    enable_client_server,
)
from ray_tpu.actor import ActorClass, ActorHandle, get_actor  # noqa: F401
from ray_tpu.object_ref import (  # noqa: F401
    ObjectRef,
    ObjectRefGenerator,
)
from ray_tpu.remote_function import RemoteFunction, remote  # noqa: F401
from ray_tpu.runtime_context import get_runtime_context  # noqa: F401


def nodes():
    from ray_tpu._private.worker import global_worker

    return global_worker().gcs.nodes()


def timeline(filename=None, job_id=None):
    """Chrome-trace dump of task execution (reference: `ray.timeline`,
    `python/ray/_private/state.py:851`). Returns the event list; with
    `filename`, writes JSON loadable in chrome://tracing or Perfetto.
    On a cluster head the dump is CLUSTER-wide: worker-node events ship
    to the head's aggregator, each trace event ``pid``-tagged with its
    executing node. ``job_id`` restricts the dump to one job's events
    (each event also carries its job tag in ``args.job``)."""
    import json

    from ray_tpu._private.obs_plane import cluster_task_events
    from ray_tpu._private.task_events import chrome_trace_events
    from ray_tpu._private.worker import global_worker

    events = cluster_task_events(global_worker())
    if job_id is not None:
        events = [ev for ev in events if ev.job_id == job_id]
    events = chrome_trace_events(events)
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events


def cluster_resources():
    from ray_tpu._private.worker import global_worker

    return global_worker().gcs.cluster_resources()


def available_resources():
    from ray_tpu._private.worker import global_worker

    return global_worker().gcs.available_resources()


__all__ = [
    "ActorClass",
    "ActorHandle",
    "ObjectRef",
    "ObjectRefGenerator",
    "RemoteFunction",
    "available_resources",
    "cancel",
    "cluster_resources",
    "enable_client_server",
    "exceptions",
    "get",
    "get_actor",
    "get_runtime_context",
    "init",
    "is_initialized",
    "kill",
    "nodes",
    "put",
    "remote",
    "shutdown",
    "timeline",
    "wait",
]
