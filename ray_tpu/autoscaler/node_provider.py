"""NodeProvider plugin ABC + implementations.

Reference: `python/ray/autoscaler/node_provider.py` (ABC), cloud providers
under `autoscaler/_private/`, and the fake multi-node provider used in
tests (`_private/fake_multi_node/node_provider.py`).
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional


class NodeProvider:
    """Creates/terminates nodes of declared node types."""

    def __init__(self, provider_config: Optional[dict] = None):
        self.provider_config = provider_config or {}

    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        raise NotImplementedError

    def create_node(self, node_type: str, count: int) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def node_tags(self, node_id: str) -> Dict[str, str]:
        return {}

    def is_running(self, node_id: str) -> bool:
        return True


class FakeNodeProvider(NodeProvider):
    """In-process provider for tests: "launching" a node grows the local
    backend's resource pool (and terminating shrinks it), so the
    autoscaler loop is exercised end-to-end without a cloud."""

    def __init__(self, node_types: Dict[str, Dict[str, float]],
                 provider_config: Optional[dict] = None):
        super().__init__(provider_config)
        self.node_types = node_types
        self._nodes: Dict[str, str] = {}  # node_id -> node_type
        self._lock = threading.Lock()

    def non_terminated_nodes(self, tag_filters=None) -> List[str]:
        with self._lock:
            return list(self._nodes)

    def create_node(self, node_type: str, count: int) -> List[str]:
        from ray_tpu._private.resources import to_milli
        from ray_tpu._private import worker as worker_mod

        resources = self.node_types[node_type]
        created = []
        with self._lock:
            for _ in range(count):
                node_id = f"fake-{node_type}-{uuid.uuid4().hex[:6]}"
                self._nodes[node_id] = node_type
                created.append(node_id)
        w = worker_mod.global_worker_or_none()
        if w is not None:
            for _ in created:
                w.backend.resources.add_capacity(to_milli(resources))
        return created

    def terminate_node(self, node_id: str) -> None:
        from ray_tpu._private.resources import to_milli
        from ray_tpu._private import worker as worker_mod

        with self._lock:
            node_type = self._nodes.pop(node_id, None)
        if node_type is None:
            return
        w = worker_mod.global_worker_or_none()
        if w is not None:
            w.backend.resources.remove_capacity(
                to_milli(self.node_types[node_type]))

    def node_tags(self, node_id: str) -> Dict[str, str]:
        with self._lock:
            t = self._nodes.get(node_id)
        return {"node-type": t} if t else {}


class TPUPodProvider(NodeProvider):
    """TPU slice provider skeleton: node types are whole slices requested
    through the Queued Resources / GKE API. Zero-egress environments stub
    the API calls; the shape of the provider (slice-at-a-time atomicity,
    topology labels) is what the autoscaler depends on."""

    def __init__(self, provider_config: Optional[dict] = None):
        super().__init__(provider_config)
        self._requested: Dict[str, dict] = {}

    def non_terminated_nodes(self, tag_filters=None) -> List[str]:
        return [k for k, v in self._requested.items()
                if v["state"] in ("REQUESTED", "ACTIVE")]

    def create_node(self, node_type: str, count: int) -> List[str]:
        # node_type e.g. "v5e-64": accelerator + chip count; topology
        # label derived for contiguous-slice placement.
        out = []
        for _ in range(count):
            node_id = f"tpu-{node_type}-{uuid.uuid4().hex[:6]}"
            self._requested[node_id] = {
                "state": "REQUESTED", "type": node_type,
                "labels": {"ici_slice": node_id},
            }
            out.append(node_id)
        return out

    def terminate_node(self, node_id: str) -> None:
        if node_id in self._requested:
            self._requested[node_id]["state"] = "TERMINATED"

    def node_tags(self, node_id: str) -> Dict[str, str]:
        info = self._requested.get(node_id, {})
        return info.get("labels", {})
