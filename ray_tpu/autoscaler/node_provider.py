"""NodeProvider plugin ABC + implementations.

Reference: `python/ray/autoscaler/node_provider.py` (ABC), cloud providers
under `autoscaler/_private/`, and the fake multi-node provider used in
tests (`_private/fake_multi_node/node_provider.py`).
"""

from __future__ import annotations

import threading
import uuid
from typing import Dict, List, Optional


class NodeProvider:
    """Creates/terminates nodes of declared node types."""

    def __init__(self, provider_config: Optional[dict] = None):
        self.provider_config = provider_config or {}

    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        raise NotImplementedError

    def create_node(self, node_type: str, count: int) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def node_tags(self, node_id: str) -> Dict[str, str]:
        return {}

    def is_running(self, node_id: str) -> bool:
        return True


class FakeNodeProvider(NodeProvider):
    """In-process provider for tests: "launching" a node grows the local
    backend's resource pool (and terminating shrinks it), so the
    autoscaler loop is exercised end-to-end without a cloud."""

    def __init__(self, node_types: Dict[str, Dict[str, float]],
                 provider_config: Optional[dict] = None):
        super().__init__(provider_config)
        self.node_types = node_types
        self._nodes: Dict[str, str] = {}  # node_id -> node_type
        self._lock = threading.Lock()

    def non_terminated_nodes(self, tag_filters=None) -> List[str]:
        with self._lock:
            return list(self._nodes)

    def create_node(self, node_type: str, count: int) -> List[str]:
        from ray_tpu._private.resources import to_milli
        from ray_tpu._private import worker as worker_mod

        resources = self.node_types[node_type]
        created = []
        with self._lock:
            for _ in range(count):
                node_id = f"fake-{node_type}-{uuid.uuid4().hex[:6]}"
                self._nodes[node_id] = node_type
                created.append(node_id)
        w = worker_mod.global_worker_or_none()
        if w is not None:
            for _ in created:
                w.backend.resources.add_capacity(to_milli(resources))
        return created

    def terminate_node(self, node_id: str) -> None:
        from ray_tpu._private.resources import to_milli
        from ray_tpu._private import worker as worker_mod

        with self._lock:
            node_type = self._nodes.pop(node_id, None)
        if node_type is None:
            return
        w = worker_mod.global_worker_or_none()
        if w is not None:
            w.backend.resources.remove_capacity(
                to_milli(self.node_types[node_type]))

    def node_tags(self, node_id: str) -> Dict[str, str]:
        with self._lock:
            t = self._nodes.get(node_id)
        return {"node-type": t} if t else {}


class ClusterNodeProvider(NodeProvider):
    """Launch REAL node processes into a `cluster_utils.Cluster`
    (reference: the fake multi-node provider,
    `autoscaler/_private/fake_multi_node/node_provider.py`, which runs
    actual raylets). Each create_node spawns a node subprocess that
    registers with the head; terminate shuts it down. This is the
    provider the end-to-end autoscaler test drives."""

    def __init__(self, cluster, node_types: Dict[str, Dict[str, float]],
                 provider_config: Optional[dict] = None):
        super().__init__(provider_config)
        self.cluster = cluster
        self.node_types = node_types
        self._types: Dict[str, str] = {}  # node_id -> node_type
        self._lock = threading.Lock()

    def non_terminated_nodes(self, tag_filters=None) -> List[str]:
        with self._lock:
            return [n for n in self._types
                    if self.cluster.head.nodes.get(n) is not None
                    and self.cluster.head.nodes[n].alive]

    def create_node(self, node_type: str, count: int) -> List[str]:
        res = dict(self.node_types[node_type])
        created = []
        for _ in range(count):
            node_id = self.cluster.add_node(
                num_cpus=res.get("CPU", 1), num_tpus=res.get("TPU", 0))
            with self._lock:
                self._types[node_id] = node_type
            created.append(node_id)
        return created

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            self._types.pop(node_id, None)
        try:
            self.cluster.remove_node(node_id)
        except Exception:
            pass

    def node_tags(self, node_id: str) -> Dict[str, str]:
        with self._lock:
            t = self._types.get(node_id)
        return {"node-type": t} if t else {}

    def is_running(self, node_id: str) -> bool:
        record = self.cluster.head.nodes.get(node_id)
        return bool(record is not None and record.alive)


def cluster_demand_fn(head):
    """Pending demands from the cluster head's view: specs queued
    cluster-wide because no node can fit them (the reference autoscaler
    reads the same from GCS resource load). The returned fn carries the
    head so `StandardAutoscaler.start/stop` can flip
    `head.autoscaling_enabled` for its lifetime (infeasible tasks wait
    for capacity only while an autoscaler actually runs)."""

    def fn() -> List[Dict[str, float]]:
        return list(head.pending_demands.values())

    fn.head = head
    return fn


class TPUPodProvider(NodeProvider):
    """TPU slice provider skeleton: node types are whole slices requested
    through the Queued Resources / GKE API. Zero-egress environments stub
    the API calls; the shape of the provider (slice-at-a-time atomicity,
    topology labels) is what the autoscaler depends on."""

    def __init__(self, provider_config: Optional[dict] = None):
        super().__init__(provider_config)
        self._requested: Dict[str, dict] = {}

    def non_terminated_nodes(self, tag_filters=None) -> List[str]:
        return [k for k, v in self._requested.items()
                if v["state"] in ("REQUESTED", "ACTIVE")]

    def create_node(self, node_type: str, count: int) -> List[str]:
        # node_type e.g. "v5e-64": accelerator + chip count; topology
        # label derived for contiguous-slice placement.
        out = []
        for _ in range(count):
            node_id = f"tpu-{node_type}-{uuid.uuid4().hex[:6]}"
            self._requested[node_id] = {
                "state": "REQUESTED", "type": node_type,
                "labels": {"ici_slice": node_id},
            }
            out.append(node_id)
        return out

    def terminate_node(self, node_id: str) -> None:
        if node_id in self._requested:
            self._requested[node_id]["state"] = "TERMINATED"

    def node_tags(self, node_id: str) -> Dict[str, str]:
        info = self._requested.get(node_id, {})
        return info.get("labels", {})
