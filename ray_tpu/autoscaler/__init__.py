"""Autoscaler: demand-driven cluster scaling.

Reference: `python/ray/autoscaler/` (SURVEY.md §2.2) — `StandardAutoscaler`
control loop reading resource load, a bin-packing demand scheduler
(`resource_demand_scheduler.py`), and a `NodeProvider` plugin ABC with
cloud implementations. The TPU shift: node types are *slices*
(`v5e-8`, `v5e-64`, ...) — atomic units with ICI topology labels — not
fungible GPU VMs, so scaling requests whole slices and placement groups
can demand contiguous ones.
"""

from ray_tpu.autoscaler.node_provider import (  # noqa: F401
    ClusterNodeProvider,
    FakeNodeProvider,
    NodeProvider,
    TPUPodProvider,
    cluster_demand_fn,
)
from ray_tpu.autoscaler.autoscaler import (  # noqa: F401
    AutoscalerConfig,
    NodeType,
    StandardAutoscaler,
)
