"""StandardAutoscaler: the scaling control loop + demand bin-packing.

Reference: `autoscaler/_private/autoscaler.py` (control loop) and
`resource_demand_scheduler.py` (pack pending demands onto node types
respecting min/max workers and `upscaling_speed`). Demand comes from the
scheduler's unfulfilled requests; supply from provider node types.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider


@dataclass
class NodeType:
    name: str
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10


@dataclass
class AutoscalerConfig:
    node_types: List[NodeType] = field(default_factory=list)
    upscaling_speed: float = 1.0
    idle_timeout_s: float = 60.0
    interval_s: float = 1.0
    # Max time to wait for an in-flight launch to register before
    # demand-packing again (stuck-launch escape hatch).
    launch_grace_s: float = 30.0


def bin_pack_demands(demands: List[Dict[str, float]],
                     node_types: List[NodeType],
                     existing: Dict[str, int]) -> Dict[str, int]:
    """Choose node launches covering `demands` (list of resource dicts).
    First-fit-decreasing onto the smallest feasible node type; respects
    per-type max_workers. Returns {node_type: count_to_launch}."""
    to_launch: Dict[str, int] = {}
    # Track remaining capacity of planned nodes.
    open_nodes: List[Dict[str, float]] = []

    def feasible(nt: NodeType, demand):
        return all(nt.resources.get(k, 0) >= v for k, v in demand.items())

    demands_sorted = sorted(
        demands, key=lambda d: -sum(d.values()))
    types_sorted = sorted(node_types,
                          key=lambda nt: sum(nt.resources.values()))
    for demand in demands_sorted:
        placed = False
        for node in open_nodes:
            if all(node.get(k, 0) >= v for k, v in demand.items()):
                for k, v in demand.items():
                    node[k] -= v
                placed = True
                break
        if placed:
            continue
        for nt in types_sorted:
            launched = existing.get(nt.name, 0) + to_launch.get(nt.name, 0)
            if feasible(nt, demand) and launched < nt.max_workers:
                to_launch[nt.name] = to_launch.get(nt.name, 0) + 1
                node = dict(nt.resources)
                for k, v in demand.items():
                    node[k] -= v
                open_nodes.append(node)
                placed = True
                break
        # Infeasible demands are simply skipped (reported upstream).
    return to_launch


class StandardAutoscaler:
    def __init__(self, provider: NodeProvider, config: AutoscalerConfig,
                 demand_fn=None):
        """`demand_fn() -> List[resource dict]`: pending unfulfilled
        requests (defaults to reading the local backend's waiting queue)."""
        self.provider = provider
        self.config = config
        self.demand_fn = demand_fn or _default_demand_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._idle_since: Dict[str, float] = {}
        self._launch_grace = None  # (node_count_at_launch, started_at)
        self.launches = 0
        self.terminations = 0

    # -- one reconcile pass ---------------------------------------------

    def update(self):
        demands = self.demand_fn()
        nodes = self.provider.non_terminated_nodes({})
        by_type: Dict[str, int] = {}
        for n in nodes:
            t = self.provider.node_tags(n).get("node-type") or \
                self.provider.node_tags(n).get("ici_slice", "unknown")
            by_type[t] = by_type.get(t, 0) + 1

        # min_workers floor
        for nt in self.config.node_types:
            deficit = nt.min_workers - by_type.get(nt.name, 0)
            if deficit > 0:
                self.provider.create_node(nt.name, deficit)
                self.launches += deficit
                by_type[nt.name] = nt.min_workers

        if demands:
            # Launch grace: a pending demand stays visible until its
            # task actually dispatches, which lags node startup +
            # registration — re-packing it every tick would launch a
            # fresh node per tick until then. Hold off while a launch is
            # in flight until the node count actually grew (or the
            # grace window expires as a stuck-launch escape hatch).
            now = time.monotonic()
            if self._launch_grace is not None:
                prev_nodes, started = self._launch_grace
                if len(nodes) > prev_nodes or \
                        now - started > self.config.launch_grace_s:
                    self._launch_grace = None
            if self._launch_grace is None:
                plan = bin_pack_demands(demands, self.config.node_types,
                                        by_type)
                launched = 0
                for name, count in plan.items():
                    count = max(1, min(
                        count,
                        math.ceil(count * self.config.upscaling_speed)))
                    self.provider.create_node(name, count)
                    launched += count
                if launched:
                    self.launches += launched
                    self._launch_grace = (len(nodes), now)
        else:
            # Idle downscaling to min_workers.
            now = time.monotonic()
            per_type_seen: Dict[str, int] = {}
            for n in nodes:
                t = self.provider.node_tags(n).get("node-type", "unknown")
                per_type_seen[t] = per_type_seen.get(t, 0) + 1
                nt = next((x for x in self.config.node_types
                           if x.name == t), None)
                if nt is None:
                    continue
                if per_type_seen[t] <= nt.min_workers:
                    self._idle_since.pop(n, None)
                    continue
                first_idle = self._idle_since.setdefault(n, now)
                if now - first_idle > self.config.idle_timeout_s:
                    self.provider.terminate_node(n)
                    self.terminations += 1
                    self._idle_since.pop(n, None)

    # -- loop ------------------------------------------------------------

    def start(self):
        self._stop.clear()
        # While (and only while) an autoscaler runs, infeasible cluster
        # tasks wait as pending demands instead of failing fast.
        head = getattr(self.demand_fn, "head", None)
        if head is not None:
            head.autoscaling_enabled = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.update()
            except Exception:  # pragma: no cover - keep the loop alive
                pass
            self._stop.wait(self.config.interval_s)

    def stop(self):
        self._stop.set()
        head = getattr(self.demand_fn, "head", None)
        if head is not None:
            head.autoscaling_enabled = False

    def summary(self) -> dict:
        nodes = self.provider.non_terminated_nodes({})
        return {
            "nodes": len(nodes),
            "launches": self.launches,
            "terminations": self.terminations,
            "pending_demands": len(self.demand_fn()),
        }


def _default_demand_fn() -> List[Dict[str, float]]:
    """Pending resource demands from the local backend: tasks waiting for
    resources (the reference reads the same from GCS resource load)."""
    from ray_tpu._private import worker as worker_mod
    from ray_tpu._private.resources import from_milli, to_milli

    w = worker_mod.global_worker_or_none()
    if w is None:
        return []
    backend = w.backend
    with backend._lock:
        waiting = list(backend._waiting_for_resources)
    return [dict(s.resources) or {"CPU": 1.0} for s in waiting]
