"""Runtime context introspection (reference: ``python/ray/runtime_context.py``)."""

from __future__ import annotations

from typing import Optional

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.task_spec import TaskKind


class RuntimeContext:
    def __init__(self, worker):
        self._worker = worker

    @property
    def job_id(self):
        return self._worker.job_id

    @property
    def node_id(self):
        ctx = self._worker.task_context.current()
        if ctx is not None and "node_id" in ctx:
            return ctx["node_id"]
        return self._worker.backend.node_id

    @property
    def namespace(self) -> str:
        return self._worker.namespace

    def get_job_id(self) -> str:
        return self._worker.job_id.hex()

    def get_node_id(self) -> str:
        return self.node_id.hex()

    def get_task_id(self) -> Optional[str]:
        ctx = self._worker.task_context.current()
        return ctx["task_spec"].task_id.hex() if ctx else None

    def get_actor_id(self) -> Optional[str]:
        ctx = self._worker.task_context.current()
        if ctx and ctx["task_spec"].kind == TaskKind.ACTOR_TASK:
            return ctx["task_spec"].actor_id.hex()
        return None

    def get_worker_id(self) -> str:
        return self._worker.worker_id.hex()

    def get_assigned_resources(self) -> dict:
        ctx = self._worker.task_context.current()
        return dict(ctx["task_spec"].resources) if ctx else {}

    @property
    def was_current_actor_reconstructed(self) -> bool:
        return False

    def get_placement_group_id(self) -> Optional[str]:
        ctx = self._worker.task_context.current()
        if ctx is None:
            return None
        strat = ctx["task_spec"].scheduling_strategy
        pg = getattr(strat, "placement_group", None)
        return pg.id.hex() if pg is not None else None


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(worker_mod.global_worker())
