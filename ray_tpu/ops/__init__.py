"""TPU kernels (Pallas) and reference implementations for the hot ops.

The reference framework delegates all device compute to torch/CUDA; here
the compute path is XLA, and the handful of ops XLA does not fuse optimally
get hand-written Pallas TPU kernels with pure-JAX reference fallbacks (used
on CPU and in interpret-mode tests):

- ``attention``     — flash attention (tiled online-softmax, MXU-shaped)
- ``norms``         — fused RMSNorm / LayerNorm
- ``rope``          — rotary position embeddings
- ``cross_entropy`` — blockwise softmax cross-entropy (no full-vocab
                      probability materialization)
"""

from ray_tpu.ops.attention import flash_attention  # noqa: F401
from ray_tpu.ops.norms import rms_norm, layer_norm  # noqa: F401
from ray_tpu.ops.rope import apply_rope, rope_frequencies  # noqa: F401
from ray_tpu.ops.cross_entropy import softmax_cross_entropy  # noqa: F401
