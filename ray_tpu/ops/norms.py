"""Fused normalization kernels (RMSNorm, LayerNorm).

XLA already fuses norm arithmetic well; the Pallas RMSNorm exists to fuse
the weight multiply and optional residual-add in one VMEM pass for the
decode hot path. The pure-JAX versions are the default on CPU and are what
autodiff differentiates through (the kernels are forward-only wrappers).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None


def rms_norm_reference(x, weight, eps: float = 1e-6):
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    out = x32 * jax.lax.rsqrt(var + eps)
    return (out * weight.astype(jnp.float32)).astype(dtype)


def layer_norm(x, weight, bias=None, eps: float = 1e-6):
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    out = (x32 - mean) * jax.lax.rsqrt(var + eps)
    out = out * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(dtype)


def _rms_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    o_ref[:] = (x * jax.lax.rsqrt(var + eps)
                * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def rms_norm_pallas(x, weight, eps: float = 1e-6, block_rows: int = 512,
                    interpret: bool = False):
    """x: [..., D]; normalizes over the last axis."""
    orig_shape = x.shape
    d = orig_shape[-1]
    x2 = x.reshape(-1, d)
    rows = x2.shape[0]
    block_rows = min(block_rows, rows)
    grid = (pl.cdiv(rows, block_rows),)
    out = pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
        interpret=interpret,
    )(x2, weight)
    return out.reshape(orig_shape)


def rms_norm(x, weight, eps: float = 1e-6, *,
             use_pallas: Optional[bool] = None, interpret: bool = False):
    if use_pallas is None:
        try:
            use_pallas = jax.devices()[0].platform == "tpu"
        except Exception:  # pragma: no cover
            use_pallas = False
    if use_pallas or interpret:
        return rms_norm_pallas(x, weight, eps, interpret=interpret)
    return rms_norm_reference(x, weight, eps)
