"""Rotary position embeddings (RoPE), split-half convention.

Pure JAX: a handful of elementwise ops XLA fuses straight into the
surrounding attention projections — a Pallas kernel would add nothing.
Frequencies are precomputed once per model and passed in (static shapes,
no recompute inside the train step).
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_seq_len: int,
                     theta: float = 500000.0, dtype=jnp.float32):
    """Returns (cos, sin) tables of shape [max_seq_len, head_dim // 2].

    theta=500000 is the Llama-3 base; Llama-2 used 10000.
    """
    return rope_from_positions(jnp.arange(max_seq_len), head_dim, theta,
                               dtype)


def rope_from_positions(positions, head_dim: int, theta: float = 500000.0,
                        dtype=jnp.float32):
    """cos/sin of shape [*positions.shape, head_dim // 2] computed
    directly from integer positions — no table gather. Elementwise, so
    it shards with the activations under SPMD; the table-gather form
    forces the partitioner into a replicate-and-repartition of the
    looked-up values when batch/seq are mesh-sharded."""
    inv_freq = 1.0 / (theta ** (
        jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    freqs = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rope(x, cos, sin, positions=None):
    """x: [B, S, H, D]; cos/sin: [max_seq, D//2] tables, or pre-selected
    [B, S, D//2] (callers doing context parallelism hoist the position
    gather out of the layer loop and shard it with the activations);
    positions: optional [B, S] int positions (for decode/packed
    sequences); defaults to arange(S)."""
    b, s, h, d = x.shape
    if cos.ndim == 3:
        assert positions is None, (
            "pre-selected 3-D cos/sin already encode positions")
        cos_sel = cos[:, :, None, :]             # [B, S, 1, D/2]
        sin_sel = sin[:, :, None, :]
    elif positions is None:
        cos_sel = cos[:s][None, :, None, :]     # [1, S, 1, D/2]
        sin_sel = sin[:s][None, :, None, :]
    else:
        cos_sel = cos[positions][:, :, None, :]  # [B, S, 1, D/2]
        sin_sel = sin[positions][:, :, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    out = jnp.concatenate(
        [x1 * cos_sel - x2 * sin_sel, x2 * cos_sel + x1 * sin_sel], axis=-1
    )
    return out.astype(x.dtype)
