"""Rotary position embeddings (RoPE), split-half convention.

Pure JAX: a handful of elementwise ops XLA fuses straight into the
surrounding attention projections — a Pallas kernel would add nothing.
Frequencies are precomputed once per model and passed in (static shapes,
no recompute inside the train step).
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_seq_len: int,
                     theta: float = 500000.0, dtype=jnp.float32):
    """Returns (cos, sin) tables of shape [max_seq_len, head_dim // 2].

    theta=500000 is the Llama-3 base; Llama-2 used 10000.
    """
    inv_freq = 1.0 / (theta ** (
        jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rope(x, cos, sin, positions=None):
    """x: [B, S, H, D]; cos/sin: [max_seq, D//2];
    positions: optional [B, S] int positions (for decode/packed sequences);
    defaults to arange(S)."""
    b, s, h, d = x.shape
    if positions is None:
        cos_sel = cos[:s][None, :, None, :]     # [1, S, 1, D/2]
        sin_sel = sin[:s][None, :, None, :]
    else:
        cos_sel = cos[positions][:, :, None, :]  # [B, S, 1, D/2]
        sin_sel = sin[positions][:, :, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    out = jnp.concatenate(
        [x1 * cos_sel - x2 * sin_sel, x2 * cos_sel + x1 * sin_sel], axis=-1
    )
    return out.astype(x.dtype)
