"""Softmax cross-entropy over large vocabularies.

The naive form materializes [tokens, vocab] probabilities (f32) — at 128k
vocab that dominates train-step memory. The blockwise form streams the
vocab dimension through a `lax.scan`, carrying only the running max /
log-sum-exp and the label logit, so peak memory is [tokens, block]. Custom
VJP recomputes per block on the backward pass (the gradient of CE is
`softmax - onehot`, emitted blockwise into the logits cotangent).

Note: when the vocab projection is tensor-sharded ("vocab" → tensor axis),
prefer computing loss inside shard_map with `lax.psum` of per-shard partial
logsumexp — the train layer wires that; this op is the per-shard building
block.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def softmax_cross_entropy_reference(logits, labels):
    """logits: [N, V] (any float dtype), labels: [N] int. Returns [N] f32."""
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    label_logit = jnp.take_along_axis(
        logits, labels[:, None], axis=-1)[:, 0]
    return lse - label_logit


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def softmax_cross_entropy(logits, labels, block_size: int = 8192):
    """Blockwise CE. logits: [N, V], labels: [N] → per-token loss [N] f32."""
    loss, _ = _ce_fwd_math(logits, labels, block_size)
    return loss


def _ce_fwd_math(logits, labels, block_size):
    n, v = logits.shape
    block_size = min(block_size, v)
    n_blocks = (v + block_size - 1) // block_size
    pad = n_blocks * block_size - v
    if pad:
        logits_p = jnp.pad(logits, ((0, 0), (0, pad)),
                           constant_values=-jnp.inf)
    else:
        logits_p = logits

    def step(carry, ib):
        m, s, lbl = carry
        # Slice + upcast one block at a time: peak extra memory is [N, B]
        # f32, not [N, V].
        blk = lax.dynamic_slice_in_dim(
            logits_p, ib * block_size, block_size, axis=1
        ).astype(jnp.float32)                       # [N, B]
        bm = blk.max(axis=-1)
        m_new = jnp.maximum(m, bm)
        s = s * jnp.exp(m - m_new) + jnp.exp(blk - m_new[:, None]).sum(-1)
        # label logit if it falls in this block
        idx = labels - ib * block_size
        in_blk = (idx >= 0) & (idx < block_size)
        gathered = jnp.take_along_axis(
            blk, jnp.clip(idx, 0, block_size - 1)[:, None], axis=-1)[:, 0]
        lbl = jnp.where(in_blk, gathered, lbl)
        return (m_new, s, lbl), None

    m0 = jnp.full((n,), -jnp.inf, jnp.float32)
    s0 = jnp.zeros((n,), jnp.float32)
    l0 = jnp.zeros((n,), jnp.float32)
    (m, s, lbl), _ = lax.scan(step, (m0, s0, l0), jnp.arange(n_blocks))
    lse = m + jnp.log(s)
    return lse - lbl, (lse,)


def _ce_vjp_fwd(logits, labels, block_size):
    loss, (lse,) = _ce_fwd_math(logits, labels, block_size)
    return loss, (logits, labels, lse)


def _ce_vjp_bwd(block_size, residuals, g):
    logits, labels, lse = residuals
    n, v = logits.shape
    # d/dlogits = softmax(logits) - onehot(labels), scaled by g per row.
    # Emitted blockwise to avoid a [N, V] f32 temp beyond the cotangent
    # itself (which is unavoidable: it's the output).
    block = min(8192, v)
    n_blocks = (v + block - 1) // block
    pad = n_blocks * block - v

    def blk_grad(ib):
        sl = lax.dynamic_slice_in_dim(logits, ib * block, block, axis=1)
        p = jnp.exp(sl.astype(jnp.float32) - lse[:, None])
        idx = labels - ib * block
        onehot = jax.nn.one_hot(jnp.where((idx >= 0) & (idx < block),
                                          idx, -1), block, dtype=jnp.float32)
        return ((p - onehot) * g[:, None]).astype(logits.dtype)

    if pad:
        logits = jnp.pad(logits, ((0, 0), (0, pad)))
    parts = [blk_grad(ib) for ib in range(n_blocks)]
    grad = jnp.concatenate(parts, axis=1)[:, :v]
    return grad, None


softmax_cross_entropy.defvjp(_ce_vjp_fwd, _ce_vjp_bwd)


# ---------------------------------------------------------------------------
# Fused projection + cross-entropy
# ---------------------------------------------------------------------------
#
# For LM training the [tokens, vocab] logits tensor is the single biggest
# activation (4x2048 tokens x 128k vocab bf16 = 2.1 GB) — and it only
# exists to feed the CE reduction. The fused form streams vocab blocks
# through the projection *and* the loss in one scan, so full logits are
# never materialized in either pass: forward keeps running (max, lse,
# label-logit); backward recomputes each block's logits, forms the local
# softmax-minus-onehot cotangent, and contracts it immediately into dx
# and dW. Costs one extra block-projection pass; saves ~4 GB of HBM
# round-trips plus the memory itself (which buys bigger batches).
#
# Sharding note: blocks slice the vocab dim, so use this only when the
# vocab dim is unsharded (tensor=1); `ray_tpu.models.loss_fn` gates on
# that and falls back to `softmax_cross_entropy` otherwise.


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_linear_cross_entropy(x, w, labels, block_size: int = 16384):
    """x: [N, D], w: [D, V], labels: [N] → per-token loss [N] f32."""
    loss, _ = _flce_fwd_math(x, w, labels, block_size)
    return loss


def _flce_blocks(w, block_size):
    d, v = w.shape
    block_size = min(block_size, v)
    n_blocks = (v + block_size - 1) // block_size
    # Prefer a nearby block count that divides V exactly: padding W costs
    # a full [D, V+pad] copy in BOTH passes (537 MB at llama3 shapes) —
    # the very memory this op exists to save. Vocab sizes are usually
    # highly composite (128256 = 8 x 16032), so a divisor close to the
    # target almost always exists.
    for nb in range(n_blocks, 4 * n_blocks + 1):
        if v % nb == 0:
            return v // nb, nb, 0
    pad = n_blocks * block_size - v
    return block_size, n_blocks, pad


def _flce_fwd_math(x, w, labels, block_size):
    n = x.shape[0]
    d, v = w.shape
    block_size, n_blocks, pad = _flce_blocks(w, block_size)
    wp = jnp.pad(w, ((0, 0), (0, pad))) if pad else w

    def step(carry, ib):
        m, s, lbl = carry
        w_blk = lax.dynamic_slice_in_dim(wp, ib * block_size, block_size,
                                         axis=1)
        blk = jnp.dot(x, w_blk,
                      preferred_element_type=jnp.float32)  # [N, B] f32
        # Padded columns would contribute exp(0); mask them to -inf.
        if pad:
            col = ib * block_size + jnp.arange(block_size)
            blk = jnp.where(col[None, :] < v, blk, -jnp.inf)
        bm = blk.max(axis=-1)
        m_new = jnp.maximum(m, bm)
        s = s * jnp.exp(m - m_new) + jnp.exp(blk - m_new[:, None]).sum(-1)
        idx = labels - ib * block_size
        in_blk = (idx >= 0) & (idx < block_size)
        gathered = jnp.take_along_axis(
            blk, jnp.clip(idx, 0, block_size - 1)[:, None], axis=-1)[:, 0]
        lbl = jnp.where(in_blk, gathered, lbl)
        return (m_new, s, lbl), None

    m0 = jnp.full((n,), -jnp.inf, jnp.float32)
    s0 = jnp.zeros((n,), jnp.float32)
    l0 = jnp.zeros((n,), jnp.float32)
    (m, s, lbl), _ = lax.scan(step, (m0, s0, l0), jnp.arange(n_blocks))
    lse = m + jnp.log(s)
    return lse - lbl, (lse,)


def _flce_vjp_fwd(x, w, labels, block_size):
    loss, (lse,) = _flce_fwd_math(x, w, labels, block_size)
    return loss, (x, w, labels, lse)


def _flce_vjp_bwd(block_size, residuals, g):
    x, w, labels, lse = residuals
    d, v = w.shape
    block_size, n_blocks, pad = _flce_blocks(w, block_size)
    wp = jnp.pad(w, ((0, 0), (0, pad))) if pad else w

    def step(carry, ib):
        dx, dwp = carry
        w_blk = lax.dynamic_slice_in_dim(wp, ib * block_size, block_size,
                                         axis=1)
        blk = jnp.dot(x, w_blk, preferred_element_type=jnp.float32)
        p = jnp.exp(blk - lse[:, None])
        if pad:
            col = ib * block_size + jnp.arange(block_size)
            p = jnp.where(col[None, :] < v, p, 0.0)
        idx = labels - ib * block_size
        onehot = jax.nn.one_hot(
            jnp.where((idx >= 0) & (idx < block_size), idx, -1),
            block_size, dtype=jnp.float32)
        dl = ((p - onehot) * g[:, None]).astype(x.dtype)  # [N, B]
        dx = dx + jnp.dot(dl, w_blk.T,
                          preferred_element_type=jnp.float32)
        dw_blk = jnp.dot(x.T, dl, preferred_element_type=jnp.float32)
        dwp = lax.dynamic_update_slice_in_dim(
            dwp, dw_blk.astype(dwp.dtype), ib * block_size, axis=1)
        return (dx, dwp), None

    dx0 = jnp.zeros(x.shape, jnp.float32)
    dw0 = jnp.zeros(wp.shape, w.dtype)
    (dx, dwp), _ = lax.scan(step, (dx0, dw0), jnp.arange(n_blocks))
    dw = dwp[:, :v] if pad else dwp
    return dx.astype(x.dtype), dw, None


fused_linear_cross_entropy.defvjp(_flce_vjp_fwd, _flce_vjp_bwd)
