"""Flash attention: tiled online-softmax attention as a Pallas TPU kernel.

Forward pass is a Pallas kernel (grid over batch × heads × q-blocks with an
inner k-block sweep; scores never hit HBM). Backward currently recomputes
the score matrix in pure JAX under XLA — correct and fusion-friendly, with
a Pallas backward kernel planned; long-context training memory is instead
handled one level up by ring attention (`ray_tpu.parallel.ring_attention`),
which only ever sees per-chunk blocks.

Layout: public API takes [batch, seq, heads, head_dim] (matching the rest
of the framework); the kernel runs in [batch, heads, seq, head_dim]. GQA is
supported by indexing the KV head as ``h * num_kv_heads // num_heads`` in
the BlockSpec index maps — no KV replication in HBM.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU backend only; absent on pure-CPU installs
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

_NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover
        return False


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                      sm_scale: float, causal: bool,
                      block_q: int, block_k: int, sk: int):
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Blocks fully above the diagonal contribute nothing under causality.
    should_compute = True
    if causal:
        should_compute = (iq + 1) * block_q > ik * block_k

    # Ragged last k-block (sk % block_k != 0): the padded columns hold
    # undefined memory and must not feed the online softmax. Statically
    # elided when shapes divide evenly.
    pad_cols = sk % block_k != 0

    @pl.when(should_compute)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)      # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)      # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)      # [bk, d]
        if pad_cols:
            # Padded K/V rows hold undefined memory; a masked p of exactly
            # 0 still yields NaN from 0 * NaN in p @ v — zero them.
            kv_rows = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, v.shape[-1]), 0)
            v = jnp.where(kv_rows < sk, v, 0.0)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale                              # [bq, bk]
        mask = None
        if causal or pad_cols:
            rows = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            if causal and pad_cols:
                mask = (rows >= cols) & (cols < sk)
            elif causal:
                mask = rows >= cols
            else:
                mask = cols < sk
            s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[:]                         # [bq, 128], lanes equal
        l_prev = l_ref[:]
        m_cur = jnp.max(s, axis=-1, keepdims=True)          # [bq, 1]
        m_next = jnp.maximum(m_prev, m_cur)                 # [bq, 128]
        p = jnp.exp(s - m_next[:, :1])                      # [bq, bk]
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        correction = jnp.exp(m_prev[:, :1] - m_next[:, :1])  # [bq, 1]
        l_ref[:] = l_prev * correction + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[:] = m_next
        acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ik == nk - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _flash_fwd(q, k, v, causal: bool, sm_scale: float,
               block_q: int, block_k: int, interpret: bool):
    """q: [B, H, S, D]; k/v: [B, Hkv, Sk, D] (already transposed)."""
    b, h, sq, d = q.shape
    _, h_kv, sk, _ = k.shape
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    grid = (b, h, pl.cdiv(sq, block_q), pl.cdiv(sk, block_k))

    kernel = functools.partial(
        _flash_fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, sk=sk,
    )
    kwargs = {}
    if pltpu is not None and not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        )
    scratch = [
        jax.ShapeDtypeStruct((block_q, 128), jnp.float32),  # m
        jax.ShapeDtypeStruct((block_q, 128), jnp.float32),  # l
        jax.ShapeDtypeStruct((block_q, d), jnp.float32),    # acc
    ]
    if pltpu is not None:
        scratch_shapes = [
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ]
    else:  # pragma: no cover - CPU interpret path without TPU plugin
        scratch_shapes = [pl.MemoryRef(s.shape, s.dtype) for s in scratch]

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda ib, ih, iq, ik: (ib, ih * h_kv // h, ik, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda ib, ih, iq, ik: (ib, ih * h_kv // h, ik, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        scratch_shapes=scratch_shapes,
        interpret=interpret,
        **kwargs,
    )(q, k, v)


# ---------------------------------------------------------------------------
# Reference math (also the backward pass, via recomputation)
# ---------------------------------------------------------------------------


def _attention_reference(q, k, v, causal: bool, sm_scale: float):
    """[B, H, S, D] layout. GQA-aware."""
    b, h, sq, d = q.shape
    h_kv = k.shape[1]
    if h_kv != h:
        rep = h // h_kv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        sk = k.shape[2]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    return _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret)


def _flash_vjp_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    o = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret)
    return o, (q, k, v)


def _flash_vjp_bwd(causal, sm_scale, block_q, block_k, interpret,
                   residuals, do):
    q, k, v = residuals

    def ref(q, k, v):
        return _attention_reference(q, k, v, causal, sm_scale)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(do)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, *, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512,
                    interpret: Optional[bool] = None,
                    use_pallas: Optional[bool] = None):
    """Flash attention over [batch, seq, heads, head_dim] tensors.

    KV tensors may have fewer heads (GQA). On non-TPU backends falls back
    to the fused-by-XLA reference unless `interpret=True` forces the kernel
    through the Pallas interpreter (used by tests).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    on_tpu = _on_tpu()
    if use_pallas is None:
        use_pallas = on_tpu or bool(interpret)
    if use_pallas:
        out = _flash(qt, kt, vt, causal, sm_scale, block_q, block_k,
                     bool(interpret) and not on_tpu)
    else:
        out = _attention_reference(qt, kt, vt, causal, sm_scale)
    return out.transpose(0, 2, 1, 3)
