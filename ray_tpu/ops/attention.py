"""Flash attention: tiled online-softmax attention as Pallas TPU kernels.

Forward pass is a Pallas kernel (grid over batch × heads × q-blocks with an
inner k-block sweep; scores never hit HBM) that also emits the per-row
logsumexp. Backward is two Pallas kernels recomputing p = exp(s - lse)
per tile: a dk/dv kernel (grid over k-blocks, inner q sweep) and a dq
kernel (grid over q-blocks, inner k sweep) — the [Sq, Sk] score matrix
never materialises in HBM in either direction. Long-context training
memory is additionally handled one level up by ring attention
(`ray_tpu.parallel.ring_attention`), which only ever sees per-chunk blocks.

Layout: public API takes [batch, seq, heads, head_dim] (matching the rest
of the framework); the kernel runs in [batch, heads, seq, head_dim]. GQA is
supported by indexing the KV head as ``h * num_kv_heads // num_heads`` in
the BlockSpec index maps — no KV replication in HBM.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl

try:  # TPU backend only; absent on pure-CPU installs
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

_NEG_INF = -1e30


def _vmem(shape, dtype):
    """VMEM scratch on TPU; generic MemoryRef under pure-CPU interpret
    installs where the TPU pallas plugin is absent (pltpu is None)."""
    if pltpu is not None:
        return pltpu.VMEM(shape, dtype)
    return pl.MemoryRef(shape, dtype)  # pragma: no cover - no-TPU installs


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover
        return False


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      m_ref, l_ref, acc_ref, *,
                      sm_scale: float, causal: bool,
                      block_q: int, block_k: int, sk: int):
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Blocks fully above the diagonal contribute nothing under causality.
    should_compute = True
    if causal:
        should_compute = (iq + 1) * block_q > ik * block_k

    # Ragged last k-block (sk % block_k != 0): the padded columns hold
    # undefined memory and must not feed the online softmax. Statically
    # elided when shapes divide evenly.
    pad_cols = sk % block_k != 0

    def compute(apply_mask):
        # Matmul inputs stay in their storage dtype (bf16 on the training
        # path) with float32 accumulation — an f32 upcast before the dot
        # would push the MXU onto its much slower fp32 path. sm_scale is
        # folded into the [bq, d] q tile instead of being spent as a full
        # [bq, bk] pass over the score matrix.
        q = q_ref[0, 0] * jnp.asarray(sm_scale, q_ref.dtype)  # [bq, d]
        k = k_ref[0, 0]                          # [bk, d]
        v = v_ref[0, 0]                          # [bk, d]
        if pad_cols:
            # Padded K/V rows hold undefined memory; a masked p of exactly
            # 0 still yields NaN from 0 * NaN in p @ v — zero them.
            kv_rows = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, v.shape[-1]), 0)
            v = jnp.where(kv_rows < sk, v, jnp.zeros_like(v))
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                         # [bq, bk]
        mask = None
        if apply_mask:
            rows = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            if causal and pad_cols:
                mask = (rows >= cols) & (cols < sk)
            elif causal:
                mask = rows >= cols
            else:
                mask = cols < sk
            s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[:]                         # [bq, 128], lanes equal
        l_prev = l_ref[:]
        m_cur = jnp.max(s, axis=-1, keepdims=True)          # [bq, 1]
        m_next = jnp.maximum(m_prev, m_cur)                 # [bq, 128]
        p = jnp.exp(s - m_next[:, :1])                      # [bq, bk]
        if mask is not None:
            # Also covers fully-masked rows (m = -inf would give p = 1).
            p = jnp.where(mask, p, 0.0)
        correction = jnp.exp(m_prev[:, :1] - m_next[:, :1])  # [bq, 1]
        l_ref[:] = l_prev * correction + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[:] = m_next
        # p in the storage dtype for the PV matmul (FlashAttention-standard;
        # keeps the MXU on its fast path), accumulate in f32.
        acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if not causal and not pad_cols:
        pl.when(should_compute)(lambda: compute(False))
    elif not causal:
        pl.when(should_compute)(lambda: compute(True))
    else:
        # The kernel is VPU-bound, so mask arithmetic is a real cost:
        # only blocks intersecting the diagonal (or the ragged tail) pay
        # for the iota/compare/select passes; blocks fully below the
        # diagonal — most of the sweep for long sequences — skip them.
        needs_mask = iq * block_q < (ik + 1) * block_k - 1
        if pad_cols:
            needs_mask = needs_mask | (ik == nk - 1)
        pl.when(should_compute & needs_mask)(lambda: compute(True))
        pl.when(should_compute & jnp.logical_not(needs_mask))(
            lambda: compute(False))

    @pl.when(ik == nk - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0, 0] = (acc_ref[:] / l[:, :1]).astype(o_ref.dtype)
        # Per-row logsumexp (lane-broadcast), consumed by the backward
        # kernels to recompute p = exp(s - lse) per tile.
        lse_ref[0, 0] = m_ref[:] + jnp.log(l)


def _flash_fwd(q, k, v, causal: bool, sm_scale: float,
               block_q: int, block_k: int, interpret: bool):
    """q: [B, H, S, D]; k/v: [B, Hkv, Sk, D] (already transposed).

    Returns ``(o, lse)`` where ``lse`` is the per-row logsumexp with shape
    ``[B, H, Sq]`` (float32), needed by the Pallas backward.
    """
    b, h, sq, d = q.shape
    _, h_kv, sk, _ = k.shape
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    grid = (b, h, pl.cdiv(sq, block_q), pl.cdiv(sk, block_k))

    def kv_index(ib, ih, iq, ik):
        if causal:
            # Blocks strictly above the diagonal are skipped by the kernel;
            # clamp their fetch index to the diagonal block so the pipeline
            # doesn't stream K/V tiles that are never read.
            ik = jnp.minimum(ik, ((iq + 1) * block_q - 1) // block_k)
        return (ib, ih * h_kv // h, ik, 0)

    kernel = functools.partial(
        _flash_fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, sk=sk,
    )
    kwargs = {}
    if pltpu is not None and not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        )
    scratch_shapes = [
        _vmem((block_q, 128), jnp.float32),  # m
        _vmem((block_q, 128), jnp.float32),  # l
        _vmem((block_q, d), jnp.float32),    # acc
    ]

    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, block_q, 128),
                         lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 128), jnp.float32),
        ],
        scratch_shapes=scratch_shapes,
        interpret=interpret,
        **kwargs,
    )(q, k, v)
    return o, lse[..., 0]


# ---------------------------------------------------------------------------
# Backward kernels
#
# Standard flash backward (reference design: the FlashAttention-2 paper's
# tiling; no code shared with any framework): with lse saved from the
# forward and delta = rowsum(do * o) precomputed,
#   p  = exp(s - lse)          s = scale * q @ k^T
#   dv = p^T @ do
#   dp = do @ v^T
#   ds = p * (dp - delta) * scale
#   dk = ds^T @ q
#   dq = ds @ k
# Split into two kernels so every output is written by exactly one grid
# lane: dk/dv (grid over k-blocks, inner q sweep) and dq (grid over
# q-blocks, inner k sweep).
# ---------------------------------------------------------------------------


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *,
                          sm_scale: float, causal: bool,
                          block_q: int, block_k: int, sq: int, sk: int):
    ik = pl.program_id(2)
    iq = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    should_compute = True
    if causal:
        should_compute = (iq + 1) * block_q > ik * block_k

    pad_rows = sq % block_q != 0

    def compute(apply_mask):
        # Storage-dtype matmul inputs, f32 accumulation; sm_scale folded
        # into the q tile (dk = ds^T @ (scale*q) is the exact gradient —
        # see the math above).
        q = q_ref[0, 0] * jnp.asarray(sm_scale, q_ref.dtype)   # [bq, d]
        k = k_ref[0, 0]                            # [bk, d]
        v = v_ref[0, 0]                            # [bk, d]
        do = do_ref[0, 0]                          # [bq, d]
        lse = lse_ref[0, 0][:, :1]                 # [bq, 1]
        delta = delta_ref[0, 0][:, :1]             # [bq, 1]
        if apply_mask and pad_rows:
            # Ragged last q-block: padded rows hold undefined memory and
            # would pollute the dk/dv column sums — zero their inputs.
            q_rows = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, q.shape[-1]), 0)
            q = jnp.where(q_rows < sq, q, jnp.zeros_like(q))
            do = jnp.where(q_rows < sq, do, jnp.zeros_like(do))

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                          # [bq, bk]
        p = jnp.exp(s - lse)

        mask = None
        if apply_mask:
            rows = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.full((block_q, block_k), True)
            if causal:
                cols = ik * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                mask &= rows >= cols
            if pad_rows:
                mask &= rows < sq
            p = jnp.where(mask, p, 0.0)

        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                          # [bq, bk]
        ds = p * (dp - delta)
        if mask is not None:
            ds = jnp.where(mask, ds, 0.0)

        # dv += p^T @ do ; dk += ds^T @ q  (contract over the q rows)
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    # Masking is needed only on diagonal-intersecting blocks and (for a
    # ragged sq) the last q block; padded k columns are column-separable
    # here — their garbage lands in dk/dv rows that are sliced off.
    if not causal and not pad_rows:
        pl.when(should_compute)(lambda: compute(False))
    else:
        needs_mask = False
        if causal:
            needs_mask = iq * block_q < (ik + 1) * block_k - 1
        if pad_rows:
            needs_mask = needs_mask | (iq == nq - 1)
        pl.when(should_compute & needs_mask)(lambda: compute(True))
        pl.when(should_compute & jnp.logical_not(needs_mask))(
            lambda: compute(False))

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_acc, *,
                         sm_scale: float, causal: bool,
                         block_q: int, block_k: int, sk: int):
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    should_compute = True
    if causal:
        should_compute = (iq + 1) * block_q > ik * block_k

    pad_cols = sk % block_k != 0

    def compute(apply_mask):
        # Storage-dtype matmul inputs, f32 accumulation; sm_scale folded
        # into the q tile, un-applied to dq in _finalize.
        q = q_ref[0, 0] * jnp.asarray(sm_scale, q_ref.dtype)   # [bq, d]
        k = k_ref[0, 0]                            # [bk, d]
        v = v_ref[0, 0]                            # [bk, d]
        do = do_ref[0, 0]                          # [bq, d]
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        if apply_mask and pad_cols:
            # Padded K/V rows hold undefined memory; dq = ds @ k mixes k
            # rows into every dq element, so zero them (ds is masked to 0
            # there, but 0 * NaN would still poison the product).
            kv_rows = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, k.shape[-1]), 0)
            k = jnp.where(kv_rows < sk, k, jnp.zeros_like(k))
            v = jnp.where(kv_rows < sk, v, jnp.zeros_like(v))

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        p = jnp.exp(s - lse)

        mask = None
        if apply_mask:
            rows = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            if causal and pad_cols:
                mask = (rows >= cols) & (cols < sk)
            elif causal:
                mask = rows >= cols
            else:
                mask = cols < sk
            p = jnp.where(mask, p, 0.0)

        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        if mask is not None:
            ds = jnp.where(mask, ds, 0.0)

        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if not causal and not pad_cols:
        pl.when(should_compute)(lambda: compute(False))
    else:
        needs_mask = False
        if causal:
            needs_mask = iq * block_q < (ik + 1) * block_k - 1
        if pad_cols:
            needs_mask = needs_mask | (ik == nk - 1)
        pl.when(should_compute & needs_mask)(lambda: compute(True))
        pl.when(should_compute & jnp.logical_not(needs_mask))(
            lambda: compute(False))

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0, 0] = (dq_acc[:] * sm_scale).astype(dq_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, causal: bool, sm_scale: float,
               block_q: int, block_k: int, interpret: bool):
    """All tensors [B, H(kv), S, D]; lse [B, H, Sq] float32."""
    b, h, sq, d = q.shape
    _, h_kv, sk, _ = k.shape
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    nq = pl.cdiv(sq, block_q)
    nk = pl.cdiv(sk, block_k)

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    lse4 = jnp.broadcast_to(lse[..., None], (b, h, sq, 128))
    delta4 = jnp.broadcast_to(delta[..., None], (b, h, sq, 128))

    kwargs = {}
    if pltpu is not None and not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        )

    def kv_index(ib, ih, iq, ik):
        if causal:
            ik = jnp.minimum(ik, ((iq + 1) * block_q - 1) // block_k)
        return (ib, ih * h_kv // h, ik, 0)

    def q_index(ib, ih, iq, ik):
        return (ib, ih, iq, 0)

    def lane_index(ib, ih, iq, ik):
        return (ib, ih, iq, 0)

    # --- dq: grid over q-blocks, inner sweep over k-blocks -----------------
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, sk=sk),
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), q_index),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
            pl.BlockSpec((1, 1, block_q, d), q_index),
            pl.BlockSpec((1, 1, block_q, 128), lane_index),
            pl.BlockSpec((1, 1, block_q, 128), lane_index),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), q_index),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        scratch_shapes=[_vmem((block_q, d), jnp.float32)],
        interpret=interpret,
        **kwargs,
    )(q, k, v, do, lse4, delta4)

    # --- dk/dv: grid over k-blocks, inner sweep over q-blocks --------------
    # For causal masks the head of the q sweep is skipped; clamp the fetch
    # index up to the first contributing q-block.
    def q_index_dkv(ib, ih, ik, iq):
        if causal:
            iq = jnp.maximum(iq, (ik * block_k) // block_q)
        return (ib, ih, iq, 0)

    def lane_index_dkv(ib, ih, ik, iq):
        if causal:
            iq = jnp.maximum(iq, (ik * block_k) // block_q)
        return (ib, ih, iq, 0)

    def kv_index_dkv(ib, ih, ik, iq):
        return (ib, ih * h_kv // h, ik, 0)

    def dkv_out_index(ib, ih, ik, iq):
        return (ib, ih, ik, 0)

    # dk/dv are produced per *query* head (float32) and group-reduced to the
    # kv heads afterwards — no KV replication in HBM on the way in.
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, sq=sq, sk=sk),
        grid=(b, h, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), q_index_dkv),
            pl.BlockSpec((1, 1, block_k, d), kv_index_dkv),
            pl.BlockSpec((1, 1, block_k, d), kv_index_dkv),
            pl.BlockSpec((1, 1, block_q, d), q_index_dkv),
            pl.BlockSpec((1, 1, block_q, 128), lane_index_dkv),
            pl.BlockSpec((1, 1, block_q, 128), lane_index_dkv),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), dkv_out_index),
            pl.BlockSpec((1, 1, block_k, d), dkv_out_index),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, sk, d), jnp.float32),
        ],
        scratch_shapes=[
            _vmem((block_k, d), jnp.float32),
            _vmem((block_k, d), jnp.float32),
        ],
        interpret=interpret,
        **kwargs,
    )(q, k, v, do, lse4, delta4)

    if h_kv != h:
        rep = h // h_kv
        dk = dk.reshape(b, h_kv, rep, sk, d).sum(axis=2)
        dv = dv.reshape(b, h_kv, rep, sk, d).sum(axis=2)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# Reference math (used on non-TPU backends and as the test oracle)
# ---------------------------------------------------------------------------


def attention_reference(q, k, v, causal: bool, sm_scale: float):
    """[B, H, S, D] layout. GQA-aware."""
    b, h, sq, d = q.shape
    h_kv = k.shape[1]
    if h_kv != h:
        rep = h // h_kv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        sk = k.shape[2]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    o, _ = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret)
    return o


def _flash_vjp_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    o, lse = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k,
                        interpret)
    # Under layer-level rematerialization, saving these two residuals (and
    # recomputing only the cheap projections for q/k/v) lets the remat
    # policy elide the forward kernel from the backward pass entirely:
    # jax.checkpoint_policies.save_only_these_names("flash_out", "flash_lse").
    o = checkpoint_name(o, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    # Optionally saveable (policy decides): skips the qkv-projection +
    # rope recompute in the backward at ~50MB/layer for typical configs.
    q = checkpoint_name(q, "flash_q")
    k = checkpoint_name(k, "flash_k")
    v = checkpoint_name(v, "flash_v")
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(causal, sm_scale, block_q, block_k, interpret,
                   residuals, do):
    q, k, v, o, lse = residuals
    return _flash_bwd(q, k, v, o, lse, do, causal, sm_scale,
                      block_q, block_k, interpret)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, *, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = 1024, block_k: int = 1024,
                    interpret: Optional[bool] = None,
                    use_pallas: Optional[bool] = None):
    """Flash attention over [batch, seq, heads, head_dim] tensors.

    KV tensors may have fewer heads (GQA). On non-TPU backends falls back
    to the fused-by-XLA reference unless `interpret=True` forces the kernel
    through the Pallas interpreter (used by tests).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    on_tpu = _on_tpu()
    if use_pallas is None:
        use_pallas = on_tpu or bool(interpret)
    if use_pallas:
        out = _flash(qt, kt, vt, causal, sm_scale, block_q, block_k,
                     bool(interpret) and not on_tpu)
    else:
        out = attention_reference(qt, kt, vt, causal, sm_scale)
    return out.transpose(0, 2, 1, 3)
