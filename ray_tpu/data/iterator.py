"""Batch iterators with background prefetch and host→device staging.

The TPU ingest hot path (SURVEY.md §5 "object/data plane": *add an
HBM-aware path*): blocks stream out of the object store on a prefetch
thread, get re-batched to a fixed batch size (static shapes for XLA), and
`jax.device_put` runs one batch ahead of the consumer so the transfer
overlaps the train step. Double-buffering is enough on TPU-VMs because
device_put is async — the consumer only blocks if compute outruns ingest.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Iterator, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import BlockAccessor

_SENTINEL = object()


def _rebatch(block_iter: Iterator[Any], batch_size: Optional[int],
             formatter, drop_last: bool) -> Iterator[Any]:
    """Accumulate blocks, emit fixed-size batches."""
    if batch_size is None:
        for block in block_iter:
            yield formatter(BlockAccessor(block))
        return
    buf = []
    buf_rows = 0
    for block in block_iter:
        buf.append(block)
        buf_rows += BlockAccessor(block).num_rows()
        while buf_rows >= batch_size:
            merged = BlockAccessor.concat(buf)
            acc = BlockAccessor(merged)
            yield formatter(BlockAccessor(acc.slice(0, batch_size)))
            rest = acc.slice(batch_size, acc.num_rows())
            buf = [rest]
            buf_rows = BlockAccessor(rest).num_rows()
    if buf_rows > 0 and not drop_last:
        merged = BlockAccessor.concat(buf)
        yield formatter(BlockAccessor(merged))


def _prefetch_iter(it: Iterator[Any], depth: int) -> Iterator[Any]:
    """Run `it` on a background thread with a bounded queue."""
    q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
    err: list = []

    def worker():
        try:
            for item in it:
                q.put(item)
        except BaseException as e:  # noqa: BLE001 - propagate to consumer
            err.append(e)
        finally:
            q.put(_SENTINEL)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    while True:
        item = q.get()
        if item is _SENTINEL:
            if err:
                raise err[0]
            return
        yield item


def iter_batches_from_refs(ref_iter, *, batch_size: Optional[int],
                           batch_format: str = "default",
                           drop_last: bool = False,
                           prefetch: int = 1) -> Iterator[Any]:
    from ray_tpu.data.dataset import _batch_formatter

    formatter = _batch_formatter(batch_format)

    def blocks():
        for ref in ref_iter:
            yield ray_tpu.get(ref)

    it = _rebatch(blocks(), batch_size, formatter, drop_last)
    if prefetch > 0:
        it = _prefetch_iter(it, prefetch)
    return it


def iter_device_batches(ref_iter, *, batch_size: Optional[int],
                        dtypes: Optional[Dict[str, Any]] = None,
                        device=None, sharding=None,
                        prefetch: int = 2,
                        drop_last: bool = True) -> Iterator[Any]:
    """Numpy batches → jax arrays on device/sharding, double-buffered."""
    import jax

    target = sharding if sharding is not None else device

    def to_device(batch: Dict[str, np.ndarray]):
        out = {}
        for k, v in batch.items():
            if dtypes and k in dtypes:
                v = v.astype(dtypes[k])
            out[k] = jax.device_put(v, target) if target is not None \
                else jax.device_put(v)
        return out

    def blocks():
        for ref in ref_iter:
            yield ray_tpu.get(ref)

    host_iter = _rebatch(blocks(), batch_size,
                         lambda acc: acc.to_numpy(), drop_last)
    staged = (to_device(b) for b in host_iter)
    # The prefetch queue holds device arrays whose transfers are already
    # enqueued — consuming one batch ahead hides H2D latency.
    return _prefetch_iter(staged, prefetch)
