"""Streaming operator-graph executor: pipelined, pull-based, bounded.

Role-equivalent to the reference's
`data/_internal/execution/streaming_executor.py:35`: the logical plan
lowers to a chain of physical operators; blocks flow through the chain as
ObjectRefs with a bounded number in flight per operator (backpressure), so
downstream consumption (e.g. train ingest) overlaps upstream reads and
transforms instead of materializing stage-by-stage.

Operator kinds:
- SourceOp: read tasks / local blocks, submitted lazily under the cap.
- MapOp: one task per block (fused transform chains arrive pre-fused).
- AllToAllOp: a barrier (shuffle/sort/repartition/zip/union): collects
  every upstream block, runs its task graph, then streams results out.
  Upstream stays pipelined while the barrier accumulates.
- LimitOp: cuts the stream after N rows without running upstream further.

Ordering is preserved (per-op FIFO completion), matching the reference's
default preserve_order semantics.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Iterator, List, Optional

import ray_tpu


class _OpStats:
    def __init__(self, name: str):
        self.name = name
        self.submitted = 0
        self.completed = 0
        self.busy_s = 0.0          # driver-observed submit→finish span
        self.peak_in_flight = 0

    def summary(self) -> dict:
        return {"name": self.name, "blocks": self.completed,
                "wall_s": round(self.busy_s, 4),
                "peak_in_flight": self.peak_in_flight}


class PhysicalOp:
    """Base: pull-based operator with a bounded in-flight window."""

    def __init__(self, name: str, max_in_flight: int = 8):
        self.name = name
        self.max_in_flight = max_in_flight
        self.inputs: deque = deque()       # refs waiting to process
        self.in_flight: deque = deque()    # (ref, t_submit) FIFO
        self.outputs: deque = deque()      # completed refs
        self.upstream_done = False
        self.stats = _OpStats(name)

    # -- hooks -----------------------------------------------------------

    def submit_one(self) -> bool:
        """Launch one unit of work if possible. Returns True if launched."""
        return False

    def done(self) -> bool:
        return (self.upstream_done and not self.inputs
                and not self.in_flight)

    # -- shared machinery ------------------------------------------------

    def poll(self) -> bool:
        """Move completed head-of-line work to outputs (FIFO order keeps
        the stream deterministic). Returns True if anything progressed.

        One batched, event-driven wait over the whole in-flight window
        replaces the old per-ref ``wait([ref], timeout=0)`` loop (one
        store lock round trip per ref per tick); completion is then a
        single snapshot and the FIFO prefix pops in order."""
        if not self.in_flight:
            return False
        refs = list(dict.fromkeys(ref for ref, _ in self.in_flight))
        ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=0)
        if not ready:
            return False
        ready_set = set(ready)
        progressed = False
        while self.in_flight and self.in_flight[0][0] in ready_set:
            ref, t0 = self.in_flight.popleft()
            self.outputs.append(ref)
            self.stats.completed += 1
            self.stats.busy_s += time.perf_counter() - t0
            progressed = True
        return progressed

    def _track(self, ref) -> None:
        self.in_flight.append((ref, time.perf_counter()))
        self.stats.submitted += 1
        self.stats.peak_in_flight = max(self.stats.peak_in_flight,
                                        len(self.in_flight))


class SourceOp(PhysicalOp):
    """Read tasks or pre-materialized blocks."""

    def __init__(self, name: str, read_tasks: Optional[List] = None,
                 blocks: Optional[List] = None, refs: Optional[List] = None,
                 max_in_flight: int = 8):
        super().__init__(name, max_in_flight)
        self._tasks = deque(read_tasks or [])
        self._blocks = deque(blocks or [])
        self._refs = deque(refs or [])
        self.upstream_done = True

    def submit_one(self) -> bool:
        from ray_tpu.data.plan import _read_task

        if len(self.in_flight) >= self.max_in_flight:
            return False
        if self._tasks:
            self._track(_read_task.remote(self._tasks.popleft()))
            return True
        if self._blocks:
            self._track(ray_tpu.put(self._blocks.popleft()))
            return True
        if self._refs:
            self._track(self._refs.popleft())
            return True
        return False

    def done(self) -> bool:
        return not (self._tasks or self._blocks or self._refs
                    or self.in_flight)


class MapOp(PhysicalOp):
    def __init__(self, name: str, fn: Callable, num_cpus: float = 1.0,
                 max_in_flight: int = 8):
        super().__init__(name, max_in_flight)
        self.fn = fn
        self.num_cpus = num_cpus

    def submit_one(self) -> bool:
        from ray_tpu.data.plan import _apply_fn

        if not self.inputs or len(self.in_flight) >= self.max_in_flight:
            return False
        ref = self.inputs.popleft()
        self._track(_apply_fn.options(num_cpus=self.num_cpus)
                    .remote(self.fn, ref))
        return True


class AllToAllOp(PhysicalOp):
    """Barrier operator: buffers all upstream refs, then runs
    `run_fn(refs) -> refs` (the existing two-stage shuffle/sort task
    graphs) exactly once."""

    def __init__(self, name: str, run_fn: Callable[[List], List]):
        super().__init__(name, max_in_flight=1)
        self.run_fn = run_fn
        self._buffered: List = []
        self._ran = False

    def submit_one(self) -> bool:
        while self.inputs:
            self._buffered.append(self.inputs.popleft())
        if self._ran or not self.upstream_done or self.inputs:
            return False
        t0 = time.perf_counter()
        out = self.run_fn(self._buffered)
        self._ran = True
        # Drop the input refs: holding them would pin every pre-barrier
        # block for the life of the plan (the executor is retained for
        # streaming_stats).
        self._buffered = []
        for ref in out:
            self.outputs.append(ref)
        self.stats.submitted += len(out)
        self.stats.completed += len(out)
        self.stats.busy_s += time.perf_counter() - t0
        return True

    def done(self) -> bool:
        # Done once the barrier ran and its outputs drained downstream.
        return self._ran and not self.outputs

    def poll(self) -> bool:
        return False  # no async in-flight: run_fn produced final refs


class LimitOp(PhysicalOp):
    """Row-limit: passes refs through until the limit is reached, then
    declares the whole pipeline done (upstream stops being polled)."""

    def __init__(self, name: str, limit: int):
        super().__init__(name, max_in_flight=1)
        self.limit = limit
        self._rows = 0
        self.exhausted = False

    def submit_one(self) -> bool:
        from ray_tpu.data.plan import _meta_of, _slice_concat

        if self.exhausted or not self.inputs:
            return False
        ref = self.inputs.popleft()
        # Row accounting needs only the block's length: fetch *metadata*
        # (the payload itself stays in the object store / on its node).
        rows = ray_tpu.get(_meta_of.remote(ref)).num_rows
        if rows == 0:
            # An empty block is not end-of-stream — swallow it and keep
            # pulling (the limit counts rows, not blocks).
            return True
        take = min(rows, self.limit - self._rows)
        if take <= 0:
            self.exhausted = True
            return False
        if take < rows:
            ref = _slice_concat.remote([(0, 0, take)], ref)
        self._rows += take
        self.outputs.append(ref)
        self.stats.completed += 1
        if self._rows >= self.limit:
            self.exhausted = True
        return True

    def done(self) -> bool:
        return self.exhausted or (self.upstream_done and not self.inputs
                                  and not self.in_flight)


class StreamingExecutor:
    """Drives a chain of PhysicalOps; iterate over the sink's refs."""

    def __init__(self, ops: List[PhysicalOp]):
        self.ops = ops

    def iter_refs(self, window: int = 8) -> Iterator:
        """Yield sink output refs as they complete, keeping at most
        ``window`` unconsumed sink outputs (consumer backpressure)."""
        ops = self.ops
        sink = ops[-1]
        pending_yield: deque = deque()
        while True:
            progressed = False
            # Propagate done-ness and move outputs downstream.
            for i, op in enumerate(ops):
                if i > 0:
                    up = ops[i - 1]
                    while up.outputs:
                        op.inputs.append(up.outputs.popleft())
                        progressed = True
                    op.upstream_done = up.done()
            # Poll completions sink-first (frees windows for upstream).
            for op in reversed(ops):
                if op.poll():
                    progressed = True
            # A LimitOp that hit its limit short-circuits everything
            # upstream of it.
            cut = next((i for i, op in enumerate(ops)
                        if isinstance(op, LimitOp) and op.exhausted), None)
            # Launch new work while the consumer window has room.
            room = window - len(pending_yield)
            for i, op in enumerate(ops):
                if cut is not None and i < cut:
                    continue
                if i == len(ops) - 1 and room <= 0:
                    break
                while op.submit_one():
                    progressed = True
                    if i == len(ops) - 1:
                        room -= 1
                        if room <= 0:
                            break
            while sink.outputs:
                pending_yield.append(sink.outputs.popleft())
            if pending_yield:
                yield pending_yield.popleft()
                continue
            if (cut is not None and ops[cut].done() and
                    all(op.done() for op in ops[cut:])) or \
                    all(op.done() for op in ops):
                while sink.outputs:
                    yield sink.outputs.popleft()
                return
            if not progressed:
                time.sleep(0.002)

    def stats(self) -> List[dict]:
        return [op.stats.summary() for op in self.ops]
