"""Preprocessors: fit/transform over Datasets.

Reference: `python/ray/data/preprocessors/` (scalers, encoders, imputers,
concatenator, chain, batch mapper). Fit computes statistics with Dataset
aggregates; transform lowers to `map_batches`.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np


class Preprocessor:
    _is_fitted = False

    def fit(self, ds) -> "Preprocessor":
        self._fit(ds)
        self._is_fitted = True
        return self

    def transform(self, ds):
        if not self._is_fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} must be fit first")
        return ds.map_batches(self._transform_numpy, batch_format="numpy")

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    def transform_batch(self, batch: Dict[str, np.ndarray]):
        return self._transform_numpy(dict(batch))

    def _needs_fit(self) -> bool:
        return True

    def _fit(self, ds):
        raise NotImplementedError

    def _transform_numpy(self, batch):
        raise NotImplementedError


class StandardScaler(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds):
        for c in self.columns:
            vals = ds.to_numpy(c)
            self.stats_[c] = (float(np.mean(vals)),
                              float(np.std(vals) or 1.0))

    def _transform_numpy(self, batch):
        for c in self.columns:
            mean, std = self.stats_[c]
            batch[c] = (batch[c] - mean) / (std or 1.0)
        return batch


class MinMaxScaler(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds):
        for c in self.columns:
            vals = ds.to_numpy(c)
            lo, hi = float(np.min(vals)), float(np.max(vals))
            self.stats_[c] = (lo, hi if hi > lo else lo + 1.0)

    def _transform_numpy(self, batch):
        for c in self.columns:
            lo, hi = self.stats_[c]
            batch[c] = (batch[c] - lo) / (hi - lo)
        return batch


class LabelEncoder(Preprocessor):
    def __init__(self, label_column: str):
        self.label_column = label_column
        self.classes_: Optional[np.ndarray] = None

    def _fit(self, ds):
        vals = ds.to_numpy(self.label_column)
        self.classes_ = np.unique(vals)

    def _transform_numpy(self, batch):
        lookup = {v: i for i, v in enumerate(self.classes_)}
        batch[self.label_column] = np.asarray(
            [lookup[v] for v in batch[self.label_column]])
        return batch


class OneHotEncoder(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = columns
        self.categories_: Dict[str, np.ndarray] = {}

    def _fit(self, ds):
        for c in self.columns:
            self.categories_[c] = np.unique(ds.to_numpy(c))

    def _transform_numpy(self, batch):
        for c in self.columns:
            cats = self.categories_[c]
            lookup = {v: i for i, v in enumerate(cats)}
            idx = np.asarray([lookup.get(v, -1) for v in batch[c]])
            onehot = np.zeros((len(idx), len(cats)), np.float32)
            valid = idx >= 0
            onehot[np.arange(len(idx))[valid], idx[valid]] = 1.0
            del batch[c]
            batch[c] = onehot
        return batch


class SimpleImputer(Preprocessor):
    def __init__(self, columns: List[str], strategy: str = "mean",
                 fill_value=None):
        self.columns = columns
        self.strategy = strategy
        self.fill_value = fill_value
        self.stats_: Dict[str, float] = {}

    def _needs_fit(self) -> bool:
        return self.strategy != "constant"

    def _fit(self, ds):
        import pandas as pd

        df = ds.to_pandas()
        for c in self.columns:
            if self.strategy == "mean":
                self.stats_[c] = float(df[c].mean())
            elif self.strategy == "median":
                self.stats_[c] = float(df[c].median())
            elif self.strategy == "most_frequent":
                self.stats_[c] = df[c].mode().iloc[0]

    def _transform_numpy(self, batch):
        for c in self.columns:
            fill = self.fill_value if self.strategy == "constant" \
                else self.stats_[c]
            v = batch[c].astype(float) if self.strategy != "constant" \
                else batch[c]
            mask = np.asarray([x is None or (isinstance(x, float)
                                             and np.isnan(x)) for x in v]) \
                if v.dtype == object else np.isnan(v)
            v = np.where(mask, fill, v)
            batch[c] = v
        return batch


class Concatenator(Preprocessor):
    """Merge feature columns into one vector column (model input)."""

    def __init__(self, *, include: Optional[List[str]] = None,
                 exclude: Optional[List[str]] = None,
                 output_column_name: str = "concat_out",
                 dtype=np.float32):
        self.include = include
        self.exclude = exclude or []
        self.output_column_name = output_column_name
        self.dtype = dtype

    def _needs_fit(self) -> bool:
        return False

    def _fit(self, ds):
        pass

    def _transform_numpy(self, batch):
        cols = self.include or [c for c in batch if c not in self.exclude]
        arrs = []
        for c in cols:
            v = np.asarray(batch[c])
            arrs.append(v.reshape(len(v), -1).astype(self.dtype))
            del batch[c]
        batch[self.output_column_name] = np.concatenate(arrs, axis=1)
        return batch


class BatchMapper(Preprocessor):
    def __init__(self, fn: Callable, batch_format: str = "numpy"):
        self.fn = fn
        self.batch_format = batch_format

    def _needs_fit(self) -> bool:
        return False

    def _fit(self, ds):
        pass

    def transform(self, ds):
        return ds.map_batches(self.fn, batch_format=self.batch_format)

    def _transform_numpy(self, batch):
        return self.fn(batch)


class Chain(Preprocessor):
    def __init__(self, *preprocessors: Preprocessor):
        self.preprocessors = preprocessors

    def fit(self, ds):
        for p in self.preprocessors:
            ds_t = p.fit(ds).transform(ds)
            ds = ds_t
        self._is_fitted = True
        return self

    def transform(self, ds):
        for p in self.preprocessors:
            ds = p.transform(ds)
        return ds

    def transform_batch(self, batch):
        for p in self.preprocessors:
            batch = p.transform_batch(batch)
        return batch
