"""ray_tpu.data: distributed data loading and transformation.

The Datasets-equivalent (reference `python/ray/data/`, SURVEY.md §2.4):
Arrow-backed blocks in the object store, a lazy fused execution plan over
the core task/actor runtime, streaming iteration with backpressure, and a
TPU ingest path (`Dataset.iter_jax_batches`) that stages batches host→HBM
ahead of the consumer.
"""

from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata  # noqa: F401
from ray_tpu.data.dataset import (  # noqa: F401
    Dataset,
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,
    range_tensor,
    read_binary_files,
    read_csv,
    read_datasource,
    read_images,
    read_json,
    read_numpy,
    read_parquet,
    read_sql,
    read_text,
    read_tfrecords,
    read_webdataset,
)
from ray_tpu.data.datasource import Datasource, ReadTask  # noqa: F401
from ray_tpu.data.pipeline import DatasetPipeline  # noqa: F401
from ray_tpu.data.plan import ActorPoolStrategy  # noqa: F401
from ray_tpu.data import preprocessors  # noqa: F401
from ray_tpu.data.aggregate import (  # noqa: F401
    Count,
    Max,
    Mean,
    Min,
    Std,
    Sum,
)
