"""Lazy execution plan + distributed execution over the core runtime.

Role-equivalent to the reference's `data/_internal/plan.py` (ExecutionPlan),
`_internal/logical/` (logical ops), and the execution engine
(`_internal/execution/streaming_executor.py`). Map-like operators fuse into
one task per block (the reference's operator fusion); all-to-all operators
(repartition/shuffle/sort) are stage barriers implemented as two-phase
map/reduce task graphs. Block payloads live in the object store as
ObjectRefs end-to-end — the driver only ever touches small metadata.

Streaming: `iter_block_refs` yields completed block refs with a bounded
in-flight window (backpressure), so downstream consumers (e.g. the
train-ingest iterator) pipeline against upstream compute.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, List, Optional

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data.datasource import Datasource

# ---------------------------------------------------------------------------
# Logical operators
# ---------------------------------------------------------------------------


@dataclass
class LogicalOp:
    name: str = "op"


@dataclass
class Read(LogicalOp):
    datasource: Optional[Datasource] = None
    parallelism: int = -1


@dataclass
class FromBlocks(LogicalOp):
    blocks: List[Block] = field(default_factory=list)


@dataclass
class ReadTasks(LogicalOp):
    """Read from an explicit task list (DatasetPipeline windows slice a
    datasource's read tasks into per-window plans)."""

    read_tasks: List[Any] = field(default_factory=list)


@dataclass
class MapBlocks(LogicalOp):
    """A fused block→block transform (map_batches/map/filter/flat_map all
    lower to this)."""

    fn: Optional[Callable[[Block], Block]] = None
    compute: Any = None  # None (tasks) | ActorPoolStrategy
    num_cpus: float = 1.0


@dataclass
class Repartition(LogicalOp):
    num_blocks: int = 1


@dataclass
class RandomShuffle(LogicalOp):
    seed: Optional[int] = None
    num_blocks: Optional[int] = None
    # None → RAY_TPU_PUSH_BASED_SHUFFLE env decides; True/False force.
    push_based: Optional[bool] = None


@dataclass
class Sort(LogicalOp):
    key: Optional[str] = None
    descending: bool = False


@dataclass
class Limit(LogicalOp):
    limit: int = 0


@dataclass
class Union(LogicalOp):
    others: List["ExecutionPlan"] = field(default_factory=list)


@dataclass
class Zip(LogicalOp):
    other: Optional["ExecutionPlan"] = None


class ActorPoolStrategy:
    """Reference: `data/_internal/compute.py` ActorPoolStrategy."""

    def __init__(self, size: int = 2, min_size: Optional[int] = None,
                 max_size: Optional[int] = None):
        self.size = max_size or size
        self.min_size = min_size or size


# ---------------------------------------------------------------------------
# Remote task bodies
# ---------------------------------------------------------------------------


@ray_tpu.remote
def _apply_fn(fn, block):
    return fn(block)


@ray_tpu.remote
def _read_task(task):
    blocks = list(task())
    return BlockAccessor.concat(blocks) if len(blocks) != 1 else blocks[0]


@ray_tpu.remote
def _meta_of(block):
    return BlockAccessor(block).metadata()


@ray_tpu.remote
def _slice_concat(ranges, *blocks):
    """ranges: [(block_idx, start, end)]; blocks passed as top-level args
    so ObjectRefs resolve to values before execution."""
    parts = [BlockAccessor(blocks[i]).slice(s, e) for i, s, e in ranges]
    return BlockAccessor.concat(parts)


@ray_tpu.remote
def _split_random(block, n, seed):
    import numpy as np

    acc = BlockAccessor(block)
    rows = acc.num_rows()
    # Vectorized assignment: a per-row Python randrange/list-comprehension
    # capped the whole shuffle at ~20 MB/s on GB-scale inputs.
    rng = np.random.RandomState(seed & 0x7FFFFFFF)
    assignment = rng.randint(0, n, rows)
    out = []
    for j in range(n):
        idx = np.nonzero(assignment == j)[0]
        out.append(acc.take(idx) if len(idx) else acc.slice(0, 0))
    return out


@ray_tpu.remote
def _split_by_key(block, boundaries, key, descending):
    """Range-partition a block by key into len(boundaries)+1 parts."""
    import numpy as np

    acc = BlockAccessor(block)
    vals = acc.to_numpy(key)
    part_ids = np.searchsorted(np.asarray(boundaries), vals, side="right")
    out = []
    for j in range(len(boundaries) + 1):
        idx = np.nonzero(part_ids == j)[0]
        out.append(acc.take(idx) if len(idx) else acc.slice(0, 0))
    return out


@ray_tpu.remote
def _merge_sorted(key, descending, *parts):
    block = BlockAccessor.concat(list(parts))
    from ray_tpu.data.block import _is_tensor_block

    if _is_tensor_block(block):
        # Tensor blocks sort by numpy argsort — no Arrow round trip
        # (which re-casts every multi-dim column to fixed-shape lists).
        import numpy as np

        order = np.argsort(block[key], kind="stable")
        if descending:
            order = order[::-1]
        return {k: v[order] for k, v in block.items()}
    t = BlockAccessor(block).to_arrow()
    order = "descending" if descending else "ascending"
    return t.sort_by([(key, order)])


@ray_tpu.remote
def _concat_blocks(*parts):
    return BlockAccessor.concat(list(parts))


# Push-based shuffle merge: combine one reducer's partials from every
# mapper in one round (each arg is already just that reducer's slice —
# see num_returns in _random_shuffle_push). Same body as a concat, so it
# IS the concat task under a stage-specific alias.
_merge_partials = _concat_blocks


@ray_tpu.remote
def _zip_blocks(left, right):
    import pyarrow as pa

    lt = BlockAccessor(left).to_arrow()
    rt = BlockAccessor(right).to_arrow()
    cols = {name: lt.column(name) for name in lt.column_names}
    for name in rt.column_names:
        out_name = name if name not in cols else f"{name}_1"
        cols[out_name] = rt.column(name)
    return pa.table(cols)


@ray_tpu.remote
def _sample_block(block, key, n):
    acc = BlockAccessor(block)
    rows = acc.num_rows()
    if rows == 0:
        return []
    idx = random.sample(range(rows), min(n, rows))
    vals = BlockAccessor(acc.take(idx)).to_numpy(key)
    return list(vals)


# ---------------------------------------------------------------------------
# Execution plan
# ---------------------------------------------------------------------------


class _StageStats:
    def __init__(self, name: str):
        self.name = name
        self.wall_s = 0.0
        self.num_blocks = 0
        self.num_rows = 0

    def summary(self) -> dict:
        return {"name": self.name, "wall_s": round(self.wall_s, 4),
                "blocks": self.num_blocks, "rows": self.num_rows}


class ExecutionPlan:
    def __init__(self, ops: List[LogicalOp]):
        self.ops = ops
        self._cached: Optional[List] = None  # list of block refs
        self._cached_meta: Optional[List[BlockMetadata]] = None
        self.stats: List[_StageStats] = []

    def with_op(self, op: LogicalOp) -> "ExecutionPlan":
        return ExecutionPlan(self.ops + [op])

    @property
    def streaming_stats(self) -> List[dict]:
        """Per-operator stats of the last streaming execution."""
        executor = getattr(self, "_streaming_executor", None)
        return executor.stats() if executor else []

    # -- logical optimizer + fusion --------------------------------------

    @staticmethod
    def _optimize(ops: List[LogicalOp]) -> List[LogicalOp]:
        """Logical rewrite rules (reference
        `data/_internal/logical/optimizers.py`), applied before fusion:

        - consecutive RandomShuffles collapse to the last (a second
          global shuffle of a uniform permutation adds nothing);
        - consecutive Repartitions collapse to the last.

        NOT a rule here: dropping a shuffle before a sort — the sort
        pipeline is stable end to end, so shuffle-then-sort observably
        randomizes the order WITHIN equal-key groups and removing it
        would silently change results.
        """
        out: List[LogicalOp] = []
        for op in ops:
            if out:
                prev = out[-1]
                if isinstance(op, RandomShuffle) and \
                        isinstance(prev, RandomShuffle):
                    if op.num_blocks is None and \
                            prev.num_blocks is not None:
                        # Keep the earlier shuffle's explicit output
                        # block count — the collapse must not change
                        # downstream parallelism.
                        op = RandomShuffle(
                            name=op.name, seed=op.seed,
                            num_blocks=prev.num_blocks,
                            push_based=op.push_based)
                    out[-1] = op
                    continue
                if isinstance(op, Repartition) and \
                        isinstance(prev, Repartition):
                    out[-1] = op
                    continue
            out.append(op)
        return out

    def _fused_stages(self) -> List[LogicalOp]:
        """Fuse consecutive MapBlocks with the same compute strategy."""
        stages: List[LogicalOp] = []
        for op in self._optimize(self.ops):
            if (isinstance(op, MapBlocks) and stages
                    and isinstance(stages[-1], MapBlocks)
                    and stages[-1].compute is None and op.compute is None):
                prev = stages[-1]

                def fused(block, f=prev.fn, g=op.fn):
                    return g(f(block))

                stages[-1] = MapBlocks(
                    name=f"{prev.name}->{op.name}", fn=fused,
                    num_cpus=max(prev.num_cpus, op.num_cpus))
            else:
                stages.append(op)
        return stages

    # -- execution -------------------------------------------------------

    def execute(self) -> List:
        if self._cached is None:
            refs: List = []
            self.stats = []
            for op in self._fused_stages():
                t0 = time.perf_counter()
                refs = self._execute_op(op, refs)
                st = _StageStats(op.name)
                st.wall_s = time.perf_counter() - t0
                st.num_blocks = len(refs)
                self.stats.append(st)
            self._cached = refs
        return self._cached

    def metadata(self) -> List[BlockMetadata]:
        if self._cached_meta is None:
            refs = self.execute()
            self._cached_meta = ray_tpu.get(
                [_meta_of.remote(r) for r in refs])
        return self._cached_meta

    def clear_cache(self):
        self._cached = None
        self._cached_meta = None

    def _execute_op(self, op: LogicalOp, refs: List) -> List:
        if isinstance(op, Read):
            tasks = op.datasource.get_read_tasks(op.parallelism)
            return [_read_task.remote(t) for t in tasks]
        if isinstance(op, ReadTasks):
            return [_read_task.remote(t) for t in op.read_tasks]
        if isinstance(op, FromBlocks):
            return [ray_tpu.put(b) for b in op.blocks]
        if isinstance(op, MapBlocks):
            if isinstance(op.compute, ActorPoolStrategy):
                return self._map_with_actor_pool(op, refs)
            return [_apply_fn.options(num_cpus=op.num_cpus).remote(op.fn, r)
                    for r in refs]
        if isinstance(op, Repartition):
            return self._repartition(refs, op.num_blocks)
        if isinstance(op, RandomShuffle):
            return self._random_shuffle(refs, op)
        if isinstance(op, Sort):
            return self._sort(refs, op)
        if isinstance(op, Limit):
            return self._limit(refs, op.limit)
        if isinstance(op, Union):
            out = list(refs)
            for p in op.others:
                out.extend(p.execute())
            return out
        if isinstance(op, Zip):
            return self._zip(refs, op.other)
        raise NotImplementedError(f"op {op}")

    # -- map on actor pool ----------------------------------------------

    def _map_with_actor_pool(self, op: MapBlocks, refs: List) -> List:
        from ray_tpu.util.actor_pool import ActorPool

        @ray_tpu.remote
        class _MapWorker:
            def __init__(self, fn):
                # Class-based transforms construct once per actor (the
                # reference's stateful UDF semantics).
                self.fn = fn() if isinstance(fn, type) else fn

            def apply(self, block):
                return self.fn(block)

        n = min(op.compute.size, max(1, len(refs)))
        actors = [_MapWorker.options(num_cpus=op.num_cpus).remote(op.fn)
                  for _ in range(n)]
        pool = ActorPool(actors)
        try:
            return list(pool.map_refs(lambda a, ref: a.apply.remote(ref),
                                      refs))
        finally:
            for a in actors:
                ray_tpu.kill(a)

    # -- all-to-all ------------------------------------------------------

    def _row_layout(self, refs: List) -> List[int]:
        metas = ray_tpu.get([_meta_of.remote(r) for r in refs])
        return [m.num_rows for m in metas]

    def _repartition(self, refs: List, n_out: int) -> List:
        rows = self._row_layout(refs)
        total = sum(rows)
        n_out = max(1, n_out)
        target = [total // n_out + (1 if i < total % n_out else 0)
                  for i in range(n_out)]
        # Build (input_idx, start, end) ranges per output partition.
        out_refs = []
        in_idx, in_off = 0, 0
        for tgt in target:
            need = tgt
            pieces = []
            while need > 0 and in_idx < len(refs):
                avail = rows[in_idx] - in_off
                take = min(avail, need)
                if take > 0:
                    pieces.append((refs[in_idx], in_off, in_off + take))
                    in_off += take
                    need -= take
                if in_off >= rows[in_idx]:
                    in_idx += 1
                    in_off = 0
            blocks = [p[0] for p in pieces]
            ranges = [(i, s, e) for i, (_, s, e) in enumerate(pieces)]
            out_refs.append(_slice_concat.remote(ranges, *blocks))
        return out_refs

    def _random_shuffle(self, refs: List, op: RandomShuffle) -> List:
        import os

        push = op.push_based
        if push is None:
            push = os.environ.get("RAY_TPU_PUSH_BASED_SHUFFLE",
                                  "") not in ("", "0", "false")
        if push:
            return self._random_shuffle_push(refs, op)
        n_out = op.num_blocks or max(1, len(refs))
        seed = op.seed if op.seed is not None else random.randrange(2**31)
        splits = [_split_random.options(num_returns=1).remote(
            r, n_out, seed + i) for i, r in enumerate(refs)]
        # splits[i] is a list of n_out sub-blocks; index remotely.
        out = []
        for j in range(n_out):
            parts = [_index_list.remote(s, j) for s in splits]
            out.append(_concat_blocks.remote(*parts))
        return out

    def _random_shuffle_push(self, refs: List, op: RandomShuffle,
                             merge_factor: int = 4) -> List:
        """Push-based shuffle (reference
        `data/_internal/push_based_shuffle.py`): mappers are grouped
        into ROUNDS of `merge_factor`; each round's per-reducer partials
        are pushed into one merge task per reducer, so the final reduce
        concatenates R round-partials instead of M map-partials. Task
        count drops from O(M*N) index tasks to O(M + R*N), and — since
        every stage is async futures — round k+1's maps run while round
        k's merges execute (the reference's pipelining, falling out of
        the task graph rather than a bespoke scheduler)."""
        n_out = op.num_blocks or max(1, len(refs))
        seed = op.seed if op.seed is not None else random.randrange(2**31)
        rounds = [refs[i:i + merge_factor]
                  for i in range(0, len(refs), merge_factor)]
        if n_out == 1:
            return [_concat_blocks.remote(*refs)]
        merged: List[List] = []  # [round][reducer]
        base = 0
        for rnd in rounds:
            # num_returns=n_out: each partial is its OWN object, so a
            # merge task fetches exactly its reducer's 1/n_out of every
            # mapper — passing whole split lists would make every merge
            # pull ALL of the round's data (n_out x transfer).
            splits = [_split_random.options(num_returns=n_out).remote(
                r, n_out, seed + base + i) for i, r in enumerate(rnd)]
            base += len(rnd)
            merged.append([
                _merge_partials.remote(*[s[j] for s in splits])
                for j in range(n_out)
            ])
        return [
            _concat_blocks.remote(*[m[j] for m in merged])
            for j in range(n_out)
        ]

    def _sort(self, refs: List, op: Sort) -> List:
        if not refs:
            return refs
        n_out = len(refs)
        samples: List = []
        for s in ray_tpu.get([_sample_block.remote(r, op.key, 16)
                              for r in refs]):
            samples.extend(s)
        if not samples:
            return refs
        samples.sort()
        boundaries = [samples[int(len(samples) * (i + 1) / n_out)]
                      for i in range(n_out - 1)]
        splits = [_split_by_key.remote(r, boundaries, op.key, op.descending)
                  for r in refs]
        out = []
        part_order = range(n_out - 1, -1, -1) if op.descending \
            else range(n_out)
        for j in part_order:
            parts = [_index_list.remote(s, j) for s in splits]
            out.append(_merge_sorted.remote(op.key, op.descending, *parts))
        return out

    def _limit(self, refs: List, limit: int) -> List:
        rows = self._row_layout(refs)
        out, acc = [], 0
        for r, n in zip(refs, rows):
            if acc >= limit:
                break
            take = min(n, limit - acc)
            if take == n:
                out.append(r)
            else:
                out.append(_slice_concat.remote([(0, 0, take)], r))
            acc += take
        return out

    def _zip(self, refs: List, other: "ExecutionPlan") -> List:
        right_refs = other.execute()
        left_rows = self._row_layout(refs)
        # Align the right side to the left side's row layout.
        right_aligned = ExecutionPlan([])
        right_aligned._cached = right_refs
        rows_total = sum(left_rows)
        right_rows = right_aligned._row_layout(right_refs)
        if sum(right_rows) != rows_total:
            raise ValueError(
                f"zip requires equal row counts: {rows_total} vs "
                f"{sum(right_rows)}")
        # Repartition right to match left block sizes.
        sizes = left_rows
        aligned = []
        in_idx, in_off = 0, 0
        for tgt in sizes:
            need, pieces = tgt, []
            while need > 0 and in_idx < len(right_refs):
                avail = right_rows[in_idx] - in_off
                take = min(avail, need)
                if take > 0:
                    pieces.append((right_refs[in_idx], in_off,
                                   in_off + take))
                    in_off += take
                    need -= take
                if in_off >= right_rows[in_idx]:
                    in_idx += 1
                    in_off = 0
            aligned.append(_slice_concat.remote(
                [(i, s, e) for i, (_, s, e) in enumerate(pieces)],
                *[p[0] for p in pieces]))
        return [_zip_blocks.remote(l, r) for l, r in zip(refs, aligned)]

    # -- streaming -------------------------------------------------------

    def to_physical(self):
        """Lower the fused logical plan to a physical operator chain for
        the streaming executor (reference: plan → operators lowering in
        `_internal/execution/legacy_compat.py` + operators/)."""
        from ray_tpu.data.streaming_executor import (
            AllToAllOp,
            LimitOp,
            MapOp,
            SourceOp,
        )

        def label(op, kind):
            return op.name if op.name and op.name != "op" else kind

        phys = []
        if self._cached is not None:
            phys.append(SourceOp("cached", refs=list(self._cached)))
            return phys
        for op in self._fused_stages():
            if isinstance(op, Read):
                phys.append(SourceOp(
                    label(op, "read"),
                    read_tasks=list(op.datasource.get_read_tasks(
                        op.parallelism))))
            elif isinstance(op, ReadTasks):
                phys.append(SourceOp(label(op, "read_tasks"),
                                     read_tasks=list(op.read_tasks)))
            elif isinstance(op, FromBlocks):
                phys.append(SourceOp(label(op, "from_blocks"),
                                     blocks=list(op.blocks)))
            elif isinstance(op, MapBlocks):
                if isinstance(op.compute, ActorPoolStrategy):
                    phys.append(AllToAllOp(
                        label(op, "map(actor_pool)"),
                        lambda refs, op=op:
                        self._map_with_actor_pool(op, refs)))
                else:
                    phys.append(MapOp(label(op, "map"), op.fn,
                                      num_cpus=op.num_cpus))
            elif isinstance(op, Limit):
                phys.append(LimitOp(label(op, "limit"), op.limit))
            elif isinstance(op, Repartition):
                phys.append(AllToAllOp(
                    label(op, "repartition"),
                    lambda refs, op=op:
                    self._repartition(refs, op.num_blocks)))
            elif isinstance(op, RandomShuffle):
                phys.append(AllToAllOp(
                    label(op, "random_shuffle"),
                    lambda refs, op=op: self._random_shuffle(refs, op)))
            elif isinstance(op, Sort):
                phys.append(AllToAllOp(
                    label(op, "sort"),
                    lambda refs, op=op: self._sort(refs, op)))
            elif isinstance(op, Union):
                phys.append(AllToAllOp(
                    label(op, "union"),
                    lambda refs, op=op: refs + [
                        r for p in op.others for r in p.execute()]))
            elif isinstance(op, Zip):
                phys.append(AllToAllOp(
                    label(op, "zip"),
                    lambda refs, op=op: self._zip(refs, op.other)))
            else:  # pragma: no cover
                raise NotImplementedError(f"op {op}")
        return phys

    def iter_block_refs(self, window: int = 8) -> Iterator:
        """Yield block refs in order through the streaming operator-graph
        executor: every map stage pipelines with a bounded in-flight
        window; all-to-all stages barrier (accumulating while upstream
        still streams) then stream their outputs. Per-op stats land in
        `self.streaming_stats`."""
        from ray_tpu.data.streaming_executor import StreamingExecutor

        executor = StreamingExecutor(self.to_physical())
        self._streaming_executor = executor
        # A fully drained stream doubles as materialization: repeated
        # iteration (multi-epoch ingest) must not re-run shuffles/sorts.
        out: List = []
        for ref in executor.iter_refs(window=window):
            out.append(ref)
            yield ref
        self._cached = out


@ray_tpu.remote
def _index_list(lst, j):
    return lst[j]
