"""Block model: the unit of distributed data.

Role-equivalent to the reference's `python/ray/data/block.py:99` (Block =
list | Arrow table | pandas DataFrame) and `BlockAccessor` (`block.py:237`,
Arrow impl `_internal/arrow_block.py`). Arrow is the canonical format —
zero-copy into numpy and, downstream, into pinned host staging buffers for
device transfer. Lists/DataFrames are accepted and normalized lazily.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np

Block = Any  # pyarrow.Table | pandas.DataFrame | list


@dataclass
class BlockMetadata:
    """Reference: `data/block.py` BlockMetadata."""

    num_rows: Optional[int] = None
    size_bytes: Optional[int] = None
    schema: Any = None
    input_files: List[str] = field(default_factory=list)
    exec_stats: Optional[dict] = None




def _is_tensor_block(b) -> bool:
    """Dict-of-ndarray blocks are first-class (the reference's Arrow
    tensor-extension role, `air/util/tensor_extensions/arrow.py`):
    multi-dim columns stay numpy end to end — slicing, shuffling and
    concat run at memcpy speed instead of round-tripping through Arrow
    fixed-shape-list casts (measured ~6 s of casts per GB shuffled)."""
    return isinstance(b, dict) and b and all(
        isinstance(v, np.ndarray) for v in b.values())

class BlockAccessor:
    """Uniform view over a block. `BlockAccessor.for_block(b)`."""

    def __init__(self, block: Block):
        self._block = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    # -- introspection ---------------------------------------------------

    def num_rows(self) -> int:
        import pyarrow as pa

        b = self._block
        if isinstance(b, pa.Table):
            return b.num_rows
        if _is_tensor_block(b):
            return len(next(iter(b.values())))
        try:
            import pandas as pd

            if isinstance(b, pd.DataFrame):
                return len(b)
        except ImportError:  # pragma: no cover
            pass
        return len(b)

    def size_bytes(self) -> int:
        import pyarrow as pa

        b = self._block
        if isinstance(b, pa.Table):
            return b.nbytes
        if _is_tensor_block(b):
            return sum(v.nbytes for v in b.values())
        try:
            import pandas as pd

            if isinstance(b, pd.DataFrame):
                return int(b.memory_usage(deep=True).sum())
        except ImportError:  # pragma: no cover
            pass
        return sum(sys.getsizeof(r) for r in b)

    def schema(self):
        import pyarrow as pa

        b = self._block
        if isinstance(b, pa.Table):
            return b.schema
        if _is_tensor_block(b):
            return {k: f"{v.dtype.str}{list(v.shape[1:])}"
                    for k, v in b.items()}
        try:
            import pandas as pd

            if isinstance(b, pd.DataFrame):
                return pa.Schema.from_pandas(b)
        except (ImportError, Exception):  # pragma: no cover
            pass
        if b:
            first = b[0]
            if isinstance(first, dict):
                return {k: type(v).__name__ for k, v in first.items()}
            return type(first).__name__
        return None

    def metadata(self, input_files: Optional[List[str]] = None,
                 exec_stats: Optional[dict] = None) -> BlockMetadata:
        return BlockMetadata(
            num_rows=self.num_rows(), size_bytes=self.size_bytes(),
            schema=self.schema(), input_files=input_files or [],
            exec_stats=exec_stats,
        )

    # -- conversions -----------------------------------------------------

    def to_arrow(self):
        import pyarrow as pa

        b = self._block
        if isinstance(b, pa.Table):
            return b
        if _is_tensor_block(b):
            cols = {}
            for k, v in b.items():
                cols[k] = _numpy_to_arrow_tensor(v) if v.ndim > 1 \
                    else pa.array(v)
            return pa.table(cols)
        try:
            import pandas as pd

            if isinstance(b, pd.DataFrame):
                return pa.Table.from_pandas(b, preserve_index=False)
        except ImportError:  # pragma: no cover
            pass
        rows = [r if isinstance(r, dict) else {"item": r} for r in b]
        if not rows:
            return pa.table({})
        return pa.Table.from_pylist(rows)

    def to_pandas(self):
        import pandas as pd
        import pyarrow as pa

        b = self._block
        if isinstance(b, pd.DataFrame):
            return b
        if isinstance(b, pa.Table):
            return b.to_pandas()
        return self.to_arrow().to_pandas()

    def to_numpy(self, columns: Optional[Union[str, List[str]]] = None):
        """Dict of column -> np.ndarray (or single array for one column)."""
        b = self._block
        if _is_tensor_block(b):
            if isinstance(columns, str):
                return b[columns]
            return {c: b[c] for c in (columns or b.keys())}
        t = self.to_arrow()
        cols = ([columns] if isinstance(columns, str)
                else columns or t.column_names)
        out = {}
        for c in cols:
            col = t.column(c)
            out[c] = _arrow_column_to_numpy(col)
        if isinstance(columns, str):
            return out[columns]
        return out

    def to_batch(self) -> Dict[str, np.ndarray]:
        return self.to_numpy()

    def iter_rows(self) -> Iterator[Any]:
        import pyarrow as pa

        b = self._block
        if isinstance(b, list):
            yield from b
            return
        if _is_tensor_block(b):
            keys = list(b.keys())
            for i in range(self.num_rows()):
                yield {k: b[k][i] for k in keys}
            return
        t = b if isinstance(b, pa.Table) else self.to_arrow()
        for row in t.to_pylist():
            yield row

    # -- slicing / combination -------------------------------------------

    def slice(self, start: int, end: int) -> Block:
        import pyarrow as pa

        b = self._block
        if isinstance(b, pa.Table):
            return b.slice(start, end - start)
        if _is_tensor_block(b):
            return {k: v[start:end] for k, v in b.items()}
        try:
            import pandas as pd

            if isinstance(b, pd.DataFrame):
                return b.iloc[start:end]
        except ImportError:  # pragma: no cover
            pass
        return b[start:end]

    def take(self, indices) -> Block:
        import pyarrow as pa

        b = self._block
        if isinstance(b, pa.Table):
            return b.take(indices)
        if _is_tensor_block(b):
            idx = np.asarray(indices, dtype=np.int64)
            return {k: v[idx] for k, v in b.items()}
        try:
            import pandas as pd

            if isinstance(b, pd.DataFrame):
                return b.iloc[list(indices)]
        except ImportError:  # pragma: no cover
            pass
        return [b[i] for i in indices]

    @staticmethod
    def concat(blocks: List[Block]) -> Block:
        import pyarrow as pa

        blocks = [b for b in blocks if BlockAccessor(b).num_rows() > 0] or \
            blocks[:1]
        if not blocks:
            return []
        first = blocks[0]
        if isinstance(first, list):
            out: list = []
            for b in blocks:
                out.extend(b)
            return out
        if all(_is_tensor_block(b) for b in blocks):
            keys = list(first.keys())
            return {k: np.concatenate([b[k] for b in blocks])
                    for k in keys}
        try:
            import pandas as pd

            if isinstance(first, pd.DataFrame):
                return pd.concat(blocks, ignore_index=True)
        except ImportError:  # pragma: no cover
            pass
        tables = [BlockAccessor(b).to_arrow() for b in blocks]
        return pa.concat_tables(tables, promote_options="default")

    @staticmethod
    def batch_to_block(batch) -> Block:
        """Normalize a user-returned batch (dict of arrays / DataFrame /
        Arrow table / list) into a block."""
        import pyarrow as pa

        if isinstance(batch, (pa.Table, list)):
            return batch
        try:
            import pandas as pd

            if isinstance(batch, pd.DataFrame):
                return batch
        except ImportError:  # pragma: no cover
            pass
        if isinstance(batch, dict):
            # Keep dict-of-ndarray batches AS the block (tensor blocks):
            # no Arrow cast on the write path; conversion happens lazily
            # via to_arrow() only where Arrow is genuinely needed.
            return {k: np.asarray(v) for k, v in batch.items()}
        raise TypeError(f"unsupported batch type: {type(batch)}")


def _arrow_column_to_numpy(col) -> np.ndarray:
    """ChunkedArray -> numpy, reassembling fixed-shape tensor columns."""
    import pyarrow as pa

    combined = col.combine_chunks() if isinstance(col, pa.ChunkedArray) \
        else col
    if isinstance(combined, pa.ChunkedArray):
        combined = pa.concat_arrays(combined.chunks) if combined.chunks \
            else pa.array([])
    if isinstance(combined.type, pa.FixedShapeTensorType):
        return combined.to_numpy_ndarray()
    if pa.types.is_list(combined.type) or pa.types.is_large_list(
            combined.type):
        return np.asarray(combined.to_pylist(), dtype=object) \
            if _ragged(combined) else np.asarray(combined.to_pylist())
    try:
        return combined.to_numpy(zero_copy_only=False)
    except (pa.ArrowInvalid, NotImplementedError):
        return np.asarray(combined.to_pylist())


def _ragged(arr) -> bool:
    lengths = {len(x) if x is not None else 0 for x in arr.to_pylist()}
    return len(lengths) > 1


def _numpy_to_arrow_tensor(v: np.ndarray):
    import pyarrow as pa

    try:
        tensor_type = pa.fixed_shape_tensor(pa.from_numpy_dtype(v.dtype),
                                            v.shape[1:])
        flat = pa.array(v.reshape(len(v), -1).tolist(),
                        type=pa.list_(pa.from_numpy_dtype(v.dtype)))
        return pa.FixedShapeTensorArray.from_storage(
            tensor_type,
            flat.cast(pa.list_(pa.from_numpy_dtype(v.dtype),
                               int(np.prod(v.shape[1:])))),
        )
    except (pa.ArrowInvalid, pa.ArrowNotImplementedError, ValueError):
        return pa.array(v.tolist())
