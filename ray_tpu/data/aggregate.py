"""GroupBy + aggregations over the block model.

Reference: `data/grouped_data.py` + `data/aggregate.py` (AggregateFn,
Sum/Min/Max/Mean/Std/Count). Implementation: hash-partition blocks by key
(remote map), then per-partition pandas groupby (remote reduce) — the
pull-based shuffle pattern.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import BlockAccessor


@dataclass
class AggregateFn:
    name: str
    init: Callable[[], Any]
    accumulate: Callable[[Any, Any], Any]
    merge: Callable[[Any, Any], Any]
    finalize: Callable[[Any], Any] = lambda a: a


def Count():  # noqa: N802 - reference naming
    return ("count", None)


def Sum(on: str):  # noqa: N802
    return ("sum", on)


def Min(on: str):  # noqa: N802
    return ("min", on)


def Max(on: str):  # noqa: N802
    return ("max", on)


def Mean(on: str):  # noqa: N802
    return ("mean", on)


def Std(on: str):  # noqa: N802
    return ("std", on)


@ray_tpu.remote
def _hash_partition(block, key, n):
    acc = BlockAccessor(block)
    vals = acc.to_numpy(key)
    hashes = np.asarray([hash(v) % n for v in vals])
    out = []
    for j in range(n):
        idx = np.nonzero(hashes == j)[0].tolist()
        out.append(acc.take(idx) if idx else acc.slice(0, 0))
    return out


@ray_tpu.remote
def _list_index(lst, j):
    return lst[j]


@ray_tpu.remote
def _agg_partition(key, specs, *parts):
    import pandas as pd

    df = pd.concat([BlockAccessor(p).to_pandas() for p in parts],
                   ignore_index=True)
    if df.empty:
        return df
    g = df.groupby(key, sort=True)
    cols = {}
    for op, on in specs:
        if op == "count":
            cols["count()"] = g.size()
        else:
            series = getattr(g[on], op)()
            cols[f"{op}({on})"] = series
    out = pd.DataFrame(cols).reset_index()
    return out


@ray_tpu.remote
def _map_groups(key, fn, batch_format, *parts):
    import pandas as pd

    df = pd.concat([BlockAccessor(p).to_pandas() for p in parts],
                   ignore_index=True)
    if df.empty:
        return df
    outs = []
    for _, group in df.groupby(key, sort=True):
        if batch_format in ("numpy", "default"):
            batch = {c: group[c].to_numpy() for c in group.columns}
        else:
            batch = group
        result = fn(batch)
        outs.append(BlockAccessor(
            BlockAccessor.batch_to_block(result)).to_pandas())
    return pd.concat(outs, ignore_index=True) if outs else df.iloc[:0]


class GroupedData:
    """Reference: `data/grouped_data.py` GroupedData."""

    def __init__(self, dataset, key: str):
        self._ds = dataset
        self._key = key

    def _shuffled_partitions(self, n: Optional[int] = None) -> List:
        refs = self._ds._plan.execute()
        n = n or max(1, len(refs))
        splits = [_hash_partition.remote(r, self._key, n) for r in refs]
        parts_per_out = []
        for j in range(n):
            parts_per_out.append([_list_index.remote(s, j) for s in splits])
        return parts_per_out

    def aggregate(self, *specs) -> "Any":
        from ray_tpu.data.dataset import Dataset
        from ray_tpu.data.plan import ExecutionPlan

        parts = self._shuffled_partitions()
        refs = [_agg_partition.remote(self._key, list(specs), *p)
                for p in parts]
        plan = ExecutionPlan([])
        plan._cached = refs
        return Dataset(plan)

    def count(self):
        return self.aggregate(Count())

    def sum(self, on: str):
        return self.aggregate(Sum(on))

    def min(self, on: str):
        return self.aggregate(Min(on))

    def max(self, on: str):
        return self.aggregate(Max(on))

    def mean(self, on: str):
        return self.aggregate(Mean(on))

    def std(self, on: str):
        return self.aggregate(Std(on))

    def map_groups(self, fn: Callable, *, batch_format: str = "default"):
        from ray_tpu.data.dataset import Dataset
        from ray_tpu.data.plan import ExecutionPlan

        parts = self._shuffled_partitions()
        refs = [_map_groups.remote(self._key, fn, batch_format, *p)
                for p in parts]
        plan = ExecutionPlan([])
        plan._cached = refs
        return Dataset(plan)
