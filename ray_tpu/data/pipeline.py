"""DatasetPipeline: windowed, optionally repeated streaming execution.

Reference: `python/ray/data/dataset_pipeline.py:65` — a pipeline splits a
dataset into windows of blocks and executes transforms one window at a
time, bounding memory to a window instead of the whole dataset;
`.repeat(epochs)` re-streams for multi-epoch training. Transforms added
on the pipeline apply per window; iteration drains windows in order.
"""

from __future__ import annotations

from typing import Any, Iterator, List, Optional

from ray_tpu.data.plan import ExecutionPlan, FromBlocks, Read, ReadTasks


class DatasetPipeline:
    def __init__(self, window_plans: List[ExecutionPlan], *,
                 epochs: int = 1):
        self._window_plans = window_plans
        self._epochs = epochs
        # (method_name, args, kwargs) applied to each window Dataset
        # when it materializes.
        self._ops: List[tuple] = []

    # -- construction (used by Dataset.window / Dataset.repeat) ---------

    @staticmethod
    def from_dataset(ds, blocks_per_window: int) -> "DatasetPipeline":
        plan = ds._plan
        first, rest = plan.ops[0], plan.ops[1:]
        windows: List[ExecutionPlan] = []
        if isinstance(first, Read) and plan._cached is None:
            tasks = list(first.datasource.get_read_tasks(
                first.parallelism))
            for i in range(0, len(tasks), blocks_per_window):
                windows.append(ExecutionPlan(
                    [ReadTasks(read_tasks=tasks[i:i + blocks_per_window])]
                    + list(rest)))
        else:
            # Materialized (or non-read) source: window over its blocks.
            import ray_tpu

            refs = plan.execute()
            blocks = ray_tpu.get(list(refs))
            for i in range(0, len(blocks), blocks_per_window):
                windows.append(ExecutionPlan(
                    [FromBlocks(blocks=blocks[i:i + blocks_per_window])]))
        return DatasetPipeline(windows)

    @staticmethod
    def from_repeated(ds, epochs: int) -> "DatasetPipeline":
        return DatasetPipeline([ds._plan], epochs=epochs)

    # -- per-window transforms ------------------------------------------

    def _chain(self, method: str, *args, **kwargs) -> "DatasetPipeline":
        out = DatasetPipeline(self._window_plans, epochs=self._epochs)
        out._ops = self._ops + [(method, args, kwargs)]
        return out

    def map_batches(self, fn, **kwargs) -> "DatasetPipeline":
        return self._chain("map_batches", fn, **kwargs)

    def map(self, fn, **kwargs) -> "DatasetPipeline":
        return self._chain("map", fn, **kwargs)

    def filter(self, fn, **kwargs) -> "DatasetPipeline":
        return self._chain("filter", fn, **kwargs)

    def flat_map(self, fn, **kwargs) -> "DatasetPipeline":
        return self._chain("flat_map", fn, **kwargs)

    def random_shuffle_each_window(self, *, seed: Optional[int] = None
                                   ) -> "DatasetPipeline":
        return self._chain("random_shuffle", seed=seed)

    def repeat(self, epochs: int) -> "DatasetPipeline":
        out = DatasetPipeline(self._window_plans,
                              epochs=self._epochs * epochs)
        out._ops = list(self._ops)
        return out

    # -- iteration -------------------------------------------------------

    def _window_datasets(self) -> Iterator:
        from ray_tpu.data.dataset import Dataset

        for _ in range(self._epochs):
            for plan in self._window_plans:
                ds = Dataset(ExecutionPlan(list(plan.ops)))
                for method, args, kwargs in self._ops:
                    ds = getattr(ds, method)(*args, **kwargs)
                yield ds

    def iter_epochs(self) -> Iterator["DatasetPipeline"]:
        for _ in range(self._epochs):
            one = DatasetPipeline(self._window_plans, epochs=1)
            one._ops = list(self._ops)
            yield one

    def iter_batches(self, **kwargs) -> Iterator[Any]:
        for ds in self._window_datasets():
            yield from ds.iter_batches(**kwargs)

    def iter_rows(self) -> Iterator[Any]:
        for ds in self._window_datasets():
            yield from ds.iter_rows()

    def take(self, limit: int = 20) -> List[Any]:
        out: List[Any] = []
        for ds in self._window_datasets():
            for row in ds.iter_rows():
                out.append(row)
                if len(out) >= limit:
                    return out
        return out

    def count(self) -> int:
        return sum(ds.count() for ds in self._window_datasets())

    def num_windows(self) -> int:
        return len(self._window_plans) * self._epochs

    def stats(self) -> str:
        return (f"DatasetPipeline({len(self._window_plans)} windows x "
                f"{self._epochs} epochs, {len(self._ops)} per-window "
                "ops)")
