"""Datasources: pluggable reads producing blocks, and file writers.

Reference: `python/ray/data/datasource/` (parquet/csv/json/numpy/binary/
text readers built on pyarrow, `ReadTask` model). A `Datasource` yields
`ReadTask`s — plain callables returning an iterator of blocks — which the
execution plan schedules as remote tasks, so reads parallelize and
pipeline like any other operator.
"""

from __future__ import annotations

import glob as globlib
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, List, Optional

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata


@dataclass
class ReadTask:
    fn: Callable[[], Iterable[Block]]
    metadata: BlockMetadata = field(default_factory=BlockMetadata)

    def __call__(self) -> Iterable[Block]:
        return self.fn()


class Datasource:
    """ABC. Reference: `data/datasource/datasource.py`."""

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None


class RangeDatasource(Datasource):
    def __init__(self, n: int, *, tensor_shape: Optional[tuple] = None,
                 column: str = "id"):
        self._n = n
        self._shape = tensor_shape
        self._column = column

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        import pyarrow as pa

        n = self._n
        parallelism = max(1, min(parallelism, n or 1))
        chunk = (n + parallelism - 1) // parallelism
        tasks = []
        for start in range(0, n, chunk):
            end = min(start + chunk, n)

            def make(start=start, end=end):
                ids = np.arange(start, end)
                if self._shape:
                    data = np.broadcast_to(
                        ids.reshape(-1, *([1] * len(self._shape))),
                        (end - start, *self._shape)).copy()
                    return [BlockAccessor.batch_to_block(
                        {self._column: data})]
                return [pa.table({self._column: ids})]

            tasks.append(ReadTask(lambda s=start, e=end: make(s, e),
                                  BlockMetadata(num_rows=end - start)))
        return tasks


class ItemsDatasource(Datasource):
    def __init__(self, items: List[Any]):
        self._items = list(items)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        items = self._items
        n = len(items)
        parallelism = max(1, min(parallelism, n or 1))
        chunk = (n + parallelism - 1) // parallelism
        tasks = []
        for start in range(0, n, chunk):
            part = items[start:start + chunk]
            tasks.append(ReadTask(lambda p=part: [list(p)],
                                  BlockMetadata(num_rows=len(part))))
        return tasks


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _, files in os.walk(p):
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if not f.startswith("."))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(globlib.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no input files for {paths}")
    return out


# Per-file metadata discovery (parquet footers, size stats) fans out on
# a thread pool: planning a many-file directory read is IO-latency
# bound, so wall time is O(files / pool) instead of O(files)
# (reference: parquet metadata providers prefetch footers in parallel).
_METADATA_POOL_SIZE = 16


def _parallel_plan(paths: List[str], plan_one) -> List[List[ReadTask]]:
    """Run ``plan_one(path) -> [ReadTask]`` for every path, preserving
    path order in the result. Serial under 2 paths (no pool tax)."""
    if len(paths) < 2:
        return [plan_one(p) for p in paths]
    from concurrent.futures import ThreadPoolExecutor

    workers = min(_METADATA_POOL_SIZE, len(paths))
    with ThreadPoolExecutor(max_workers=workers,
                            thread_name_prefix="ds-metadata") as pool:
        return list(pool.map(plan_one, paths))


class FileDatasource(Datasource):
    """Shared path-expansion + per-file read tasks."""

    def __init__(self, paths, **read_options):
        self._paths = _expand_paths(paths)
        self._options = read_options

    def _read_file(self, path: str) -> Iterable[Block]:
        raise NotImplementedError

    def _plan_file(self, path: str) -> List[ReadTask]:
        """Read tasks for ONE file; subclasses needing per-file metadata
        IO (e.g. parquet footers) override this and get it fanned out on
        the discovery pool."""
        return [ReadTask(lambda p=path: self._read_file(p),
                         BlockMetadata(input_files=[path]))]

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        # One task per file (parquet splits further by row group);
        # per-file metadata discovery runs on the thread pool.
        tasks: List[ReadTask] = []
        for per_file in _parallel_plan(self._paths, self._plan_file):
            tasks.extend(per_file)
        return tasks


class ParquetDatasource(FileDatasource):
    """Parquet with metadata-driven row-group splitting (reference
    `datasource/parquet_datasource.py`'s metadata provider): footers are
    read up front — cheap, no data pages — so each ROW GROUP becomes its
    own read task with known row counts, giving intra-file parallelism
    and accurate pre-execution metadata."""

    def _read_file(self, path: str) -> Iterable[Block]:
        import pyarrow.parquet as pq

        columns = self._options.get("columns")
        table = pq.read_table(path, columns=columns)
        yield table

    def _plan_file(self, path: str) -> List[ReadTask]:
        import pyarrow.parquet as pq

        columns = self._options.get("columns")
        try:
            meta = pq.ParquetFile(path).metadata
            n_groups = meta.num_row_groups
        except Exception:
            n_groups = 0
        if n_groups <= 1:
            n_rows = meta.num_rows if n_groups else None
            return [ReadTask(lambda p=path: self._read_file(p),
                             BlockMetadata(input_files=[path],
                                           num_rows=n_rows))]
        tasks: List[ReadTask] = []
        for g in range(n_groups):
            def read_group(p=path, g=g):
                f = pq.ParquetFile(p)
                yield f.read_row_group(g, columns=columns)

            tasks.append(ReadTask(
                read_group,
                BlockMetadata(
                    input_files=[path],
                    num_rows=meta.row_group(g).num_rows)))
        return tasks


class WebDatasetDatasource(FileDatasource):
    """POSIX-tar shards in the WebDataset convention (reference
    `datasource/webdataset_datasource.py`): files sharing a basename
    form one sample; the extension names the column. Decoding is
    suffix-driven: .json → parsed, .txt/.cls → str/int, image
    extensions → HWC uint8 (PIL when present), everything else raw
    bytes. One read task per shard."""

    _IMG_EXTS = {"jpg", "jpeg", "png", "ppm", "pgm", "bmp"}

    def _decode(self, ext: str, data: bytes):
        # Multi-dot extensions ("seg.png", "gen.jpg") dispatch on the
        # LAST segment (reference webdataset decoders do the same); the
        # full extension stays as the column name.
        ext = ext.rsplit(".", 1)[-1].lower()
        if ext == "json":
            import json

            return json.loads(data)
        if ext in ("txt", "text"):
            return data.decode("utf-8", "replace")
        if ext in ("cls", "id", "index"):
            try:
                return int(data.decode().strip())
            except ValueError:
                return data.decode("utf-8", "replace")
        if ext in self._IMG_EXTS:
            try:
                import io

                from PIL import Image

                return np.asarray(Image.open(io.BytesIO(data)))
            except Exception:
                return data
        if ext in ("npy",):
            import io

            return np.load(io.BytesIO(data), allow_pickle=False)
        return data

    def _read_file(self, path: str) -> Iterable[Block]:
        import tarfile

        rows: List[dict] = []
        current_key = None
        current: dict = {}
        with tarfile.open(path, "r:*") as tf:
            for member in tf:
                if not member.isfile():
                    continue
                name = member.name
                base, _, ext = name.partition(".")
                if current_key is not None and base != current_key:
                    rows.append(current)
                    current = {}
                current_key = base
                data = tf.extractfile(member).read()
                current["__key__"] = base
                current[ext] = self._decode(ext, data)
        if current:
            rows.append(current)
        yield rows  # list-of-dict block (heterogeneous decoded values)


class SQLDatasource(Datasource):
    """DBAPI-2 query reads (reference `datasource/sql_datasource.py`):
    ``connection_factory`` returns a fresh DBAPI connection inside each
    read task (connections don't pickle). Parallelism: one task per
    element of ``queries`` (the caller's own partitioning, e.g. by key
    range), or a single task for one query."""

    def __init__(self, sql, connection_factory,
                 queries: Optional[List[str]] = None):
        self._sql = sql
        self._factory = connection_factory
        self._queries = queries

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        queries = self._queries or [self._sql]
        factory = self._factory

        def make(q):
            def read() -> Iterable[Block]:
                conn = factory()
                try:
                    cur = conn.cursor()
                    cur.execute(q)
                    cols = [d[0] for d in cur.description]
                    rows = cur.fetchall()
                finally:
                    conn.close()
                yield [dict(zip(cols, r)) for r in rows]

            return read

        return [ReadTask(make(q), BlockMetadata(input_files=[]))
                for q in queries]


class CSVDatasource(FileDatasource):
    def _read_file(self, path: str) -> Iterable[Block]:
        import pyarrow.csv as pacsv

        yield pacsv.read_csv(path, **self._options)


class JSONDatasource(FileDatasource):
    def _read_file(self, path: str) -> Iterable[Block]:
        import pyarrow.json as pajson

        yield pajson.read_json(path, **self._options)


class NumpyDatasource(FileDatasource):
    def _read_file(self, path: str) -> Iterable[Block]:
        arr = np.load(path, allow_pickle=False)
        yield BlockAccessor.batch_to_block({"data": arr})


class BinaryDatasource(FileDatasource):
    def _read_file(self, path: str) -> Iterable[Block]:
        import pyarrow as pa

        with open(path, "rb") as f:
            data = f.read()
        yield pa.table({"bytes": pa.array([data], type=pa.binary()),
                        "path": [path]})


class TextDatasource(FileDatasource):
    def _read_file(self, path: str) -> Iterable[Block]:
        import pyarrow as pa

        with open(path, "r", errors="replace") as f:
            lines = [ln.rstrip("\n") for ln in f]
        yield pa.table({"text": lines})


class ImageDatasource(FileDatasource):
    """Decode images into HWC uint8 arrays (reference
    `datasource/image_datasource.py`; PIL-backed). Options: `size`
    (H, W) resize, `mode` (e.g. "RGB") conversion."""

    def _read_file(self, path: str) -> Iterable[Block]:
        from PIL import Image

        img = Image.open(path)
        mode = self._options.get("mode")
        if mode:
            img = img.convert(mode)
        size = self._options.get("size")
        if size:
            img = img.resize((size[1], size[0]))  # PIL takes (W, H)
        arr = np.asarray(img)
        # List block: HWC image arrays don't flatten into Arrow columns
        # (no tensor-extension dependency) — rows keep real ndarrays.
        yield [{"image": arr, "path": path}]


# -- TFRecord framing (no TF dependency: length-prefixed records with
# masked crc32c, the standard on-disk layout) -------------------------------

_CRC32C_POLY = 0x82F63B78
_CRC32C_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ (_CRC32C_POLY if _c & 1 else 0)
    _CRC32C_TABLE.append(_c)


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC32C_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# Upper bound on one TFRecord: the u64 length prefix of an untrusted
# file must never size an allocation unchecked.
_MAX_TFRECORD_BYTES = 1 << 31


class TFRecordDatasource(FileDatasource):
    """Raw TFRecord records as a `bytes` column (reference
    `datasource/tfrecords_datasource.py`; tf.train.Example decoding is
    the caller's map step — no TF/protobuf dependency here)."""

    def _read_file(self, path: str) -> Iterable[Block]:
        import struct as st

        import pyarrow as pa

        validate = self._options.get("validate_crc", True)
        records = []
        with open(path, "rb") as f:
            while True:
                header = f.read(12)
                if not header:
                    break
                if len(header) < 12:
                    raise ValueError(f"truncated TFRecord header in "
                                     f"{path}")
                (length,) = st.unpack("<Q", header[:8])
                (len_crc,) = st.unpack("<I", header[8:12])
                if validate and _masked_crc(header[:8]) != len_crc:
                    raise ValueError(f"bad length crc in {path}")
                if length > _MAX_TFRECORD_BYTES:
                    # The u64 prefix of a corrupt/hostile file must
                    # not size the read() allocation (the crc guard
                    # above is skippable via validate_crc=False).
                    raise ValueError(
                        f"TFRecord of {length} bytes in {path} "
                        f"exceeds the {_MAX_TFRECORD_BYTES} bound")
                data = f.read(length)
                (data_crc,) = st.unpack("<I", f.read(4))
                if validate and _masked_crc(data) != data_crc:
                    raise ValueError(f"bad record crc in {path}")
                records.append(data)
        yield pa.table({"bytes": pa.array(records, type=pa.binary())})


def write_tfrecords(records: Iterable[bytes], path: str) -> None:
    """Write raw records in TFRecord framing."""
    import struct as st

    with open(path, "wb") as f:
        for rec in records:
            header = st.pack("<Q", len(rec))
            f.write(header)
            f.write(st.pack("<I", _masked_crc(header)))
            f.write(rec)
            f.write(st.pack("<I", _masked_crc(rec)))


# ---------------------------------------------------------------------------
# Writers
# ---------------------------------------------------------------------------


def write_block_parquet(block: Block, path: str, index: int) -> str:
    import pyarrow.parquet as pq

    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{index:06d}.parquet")
    pq.write_table(BlockAccessor(block).to_arrow(), out)
    return out


def write_block_csv(block: Block, path: str, index: int) -> str:
    import pyarrow.csv as pacsv

    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{index:06d}.csv")
    pacsv.write_csv(BlockAccessor(block).to_arrow(), out)
    return out


def write_block_json(block: Block, path: str, index: int) -> str:
    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{index:06d}.json")
    BlockAccessor(block).to_pandas().to_json(out, orient="records",
                                             lines=True)
    return out
