"""Dataset: the public distributed-data API.

Role-equivalent to the reference's `python/ray/data/dataset.py` facade:
creation (range/from_*/read_*), transforms (map/map_batches/filter/...),
all-to-all (repartition/random_shuffle/sort), consumption (take/iter_*),
and ML ingest (`iter_jax_batches` — the TPU answer to
`iter_torch_batches`, `data/dataset_iterator.py:143`: prefetches blocks
from the object store and stages them host→HBM ahead of the train step).
"""

from __future__ import annotations

import builtins
from typing import Any, Callable, Iterator, List, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data import datasource as ds_mod
from ray_tpu.data.plan import (ActorPoolStrategy,
                               ExecutionPlan,
                               FromBlocks,
                               Limit,
                               MapBlocks,
                               RandomShuffle,
                               Read,
                               Repartition,
                               Sort,
                               Union as UnionOp,
                               Zip)


def _batch_formatter(fmt: str):
    if fmt in ("numpy", "default"):
        return lambda acc: acc.to_numpy()
    if fmt == "pandas":
        return lambda acc: acc.to_pandas()
    if fmt in ("pyarrow", "arrow"):
        return lambda acc: acc.to_arrow()
    raise ValueError(f"unknown batch_format {fmt!r}")


class Dataset:
    """A lazy, distributed collection of blocks."""

    def __init__(self, plan: ExecutionPlan):
        self._plan = plan

    # ------------------------------------------------------------------
    # Transforms (lazy)
    # ------------------------------------------------------------------

    def map_batches(self, fn: Union[Callable, type], *,
                    batch_size: Optional[int] = None,
                    batch_format: str = "default",
                    compute: Any = None,
                    fn_args: tuple = (), fn_kwargs: Optional[dict] = None,
                    num_cpus: float = 1.0,
                    **_ignored) -> "Dataset":
        """Reference: `data/dataset.py:376`."""
        fn_kwargs = fn_kwargs or {}
        formatter = _batch_formatter(batch_format)
        is_class = isinstance(fn, type)
        if is_class and compute is None:
            compute = ActorPoolStrategy(size=2)

        def block_fn(block: Block, _fn=fn) -> Block:
            f = _fn() if isinstance(_fn, type) else _fn
            acc = BlockAccessor(block)
            n = acc.num_rows()
            outs = []
            step = batch_size or max(n, 1)
            for start in builtins.range(0, max(n, 1), step):
                sub = BlockAccessor(acc.slice(start, min(start + step, n)))
                batch = formatter(sub)
                result = f(batch, *fn_args, **fn_kwargs)
                outs.append(BlockAccessor.batch_to_block(result))
            return BlockAccessor.concat(outs) if outs else block

        # Stateful class-based fns construct once per actor, not per block.
        if is_class:
            class _Stateful:
                def __init__(self):
                    self._inst = fn()

                def __call__(self, block: Block) -> Block:
                    acc = BlockAccessor(block)
                    n = acc.num_rows()
                    outs = []
                    step = batch_size or max(n, 1)
                    for start in builtins.range(0, max(n, 1), step):
                        sub = BlockAccessor(
                            acc.slice(start, min(start + step, n)))
                        result = self._inst(formatter(sub), *fn_args,
                                            **fn_kwargs)
                        outs.append(BlockAccessor.batch_to_block(result))
                    return BlockAccessor.concat(outs) if outs else block

            return Dataset(self._plan.with_op(MapBlocks(
                name="MapBatches", fn=_Stateful, compute=compute,
                num_cpus=num_cpus)))

        return Dataset(self._plan.with_op(MapBlocks(
            name="MapBatches", fn=block_fn, compute=compute,
            num_cpus=num_cpus)))

    def map(self, fn: Callable[[Any], Any], **kwargs) -> "Dataset":
        def block_fn(block: Block) -> Block:
            acc = BlockAccessor(block)
            rows = [fn(r) for r in acc.iter_rows()]
            if rows and isinstance(rows[0], dict):
                import pyarrow as pa

                try:
                    return pa.Table.from_pylist(rows)
                except Exception:
                    return rows
            return rows

        return Dataset(self._plan.with_op(MapBlocks(name="Map",
                                                    fn=block_fn)))

    def flat_map(self, fn: Callable[[Any], List[Any]], **kwargs) -> "Dataset":
        def block_fn(block: Block) -> Block:
            acc = BlockAccessor(block)
            rows: List[Any] = []
            for r in acc.iter_rows():
                rows.extend(fn(r))
            if rows and isinstance(rows[0], dict):
                import pyarrow as pa

                try:
                    return pa.Table.from_pylist(rows)
                except Exception:
                    return rows
            return rows

        return Dataset(self._plan.with_op(MapBlocks(name="FlatMap",
                                                    fn=block_fn)))

    def filter(self, fn: Callable[[Any], bool], **kwargs) -> "Dataset":
        def block_fn(block: Block) -> Block:
            acc = BlockAccessor(block)
            keep = [i for i, r in enumerate(acc.iter_rows()) if fn(r)]
            return acc.take(keep) if keep else acc.slice(0, 0)

        return Dataset(self._plan.with_op(MapBlocks(name="Filter",
                                                    fn=block_fn)))

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def block_fn(block: Block) -> Block:
            acc = BlockAccessor(block)
            df = acc.to_pandas()
            df = df.copy()
            df[name] = fn(df)
            return df

        return Dataset(self._plan.with_op(MapBlocks(name="AddColumn",
                                                    fn=block_fn)))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def block_fn(block: Block) -> Block:
            return BlockAccessor(block).to_arrow().drop_columns(cols)

        return Dataset(self._plan.with_op(MapBlocks(name="DropColumns",
                                                    fn=block_fn)))

    def select_columns(self, cols: List[str]) -> "Dataset":
        def block_fn(block: Block) -> Block:
            return BlockAccessor(block).to_arrow().select(cols)

        return Dataset(self._plan.with_op(MapBlocks(name="SelectColumns",
                                                    fn=block_fn)))

    def repartition(self, num_blocks: int, shuffle: bool = False) -> "Dataset":
        if shuffle:
            return Dataset(self._plan.with_op(RandomShuffle(
                name="ShuffleRepartition", num_blocks=num_blocks)))
        return Dataset(self._plan.with_op(Repartition(
            name="Repartition", num_blocks=num_blocks)))

    def random_shuffle(self, *, seed: Optional[int] = None,
                       push_based: Optional[bool] = None) -> "Dataset":
        """Global random shuffle. ``push_based`` selects the two-stage
        pipelined-merge shuffle (reference push_based_shuffle.py);
        None defers to RAY_TPU_PUSH_BASED_SHUFFLE."""
        return Dataset(self._plan.with_op(RandomShuffle(
            name="RandomShuffle", seed=seed, push_based=push_based)))

    def randomize_block_order(self, *, seed: Optional[int] = None) -> "Dataset":
        import random as _random

        refs = list(self._plan.execute())
        rng = _random.Random(seed)
        rng.shuffle(refs)
        plan = ExecutionPlan([])
        plan._cached = refs
        return Dataset(plan)

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return Dataset(self._plan.with_op(Sort(name="Sort", key=key,
                                               descending=descending)))

    def limit(self, n: int) -> "Dataset":
        return Dataset(self._plan.with_op(Limit(name="Limit", limit=n)))

    def union(self, *others: "Dataset") -> "Dataset":
        return Dataset(self._plan.with_op(UnionOp(
            name="Union", others=[o._plan for o in others])))

    def zip(self, other: "Dataset") -> "Dataset":
        return Dataset(self._plan.with_op(Zip(name="Zip",
                                              other=other._plan)))

    def groupby(self, key: str) -> "GroupedData":
        from ray_tpu.data.aggregate import GroupedData

        return GroupedData(self, key)

    # ------------------------------------------------------------------
    # Split (for per-worker ingest)
    # ------------------------------------------------------------------

    def split(self, n: int, *, equal: bool = False,
              locality_hints=None) -> List["Dataset"]:
        """Reference: `data/dataset.py:1221`."""
        ds = self.repartition(n) if equal else self
        refs = ds._plan.execute()
        if len(refs) < n:
            ds = self.repartition(n)
            refs = ds._plan.execute()
        chunks = np.array_split(np.arange(len(refs)), n)
        out = []
        for idx in chunks:
            plan = ExecutionPlan([])
            plan._cached = [refs[i] for i in idx]
            out.append(Dataset(plan))
        return out

    def split_at_indices(self, indices: List[int]) -> List["Dataset"]:
        bounds = [0] + list(indices) + [self.count()]
        out = []
        for s, e in zip(bounds[:-1], bounds[1:]):
            sub = self.limit(e)._drop_first(s)
            out.append(sub)
        return out

    def _drop_first(self, n: int) -> "Dataset":
        if n == 0:
            return self

        counter = {"dropped": 0}

        def block_fn(block: Block) -> Block:
            acc = BlockAccessor(block)
            todo = n - counter["dropped"]
            if todo <= 0:
                return block
            rows = acc.num_rows()
            take = min(rows, todo)
            counter["dropped"] += take
            return acc.slice(take, rows)

        # Works only on materialized sequential traversal: force execute.
        refs = self._plan.execute()
        metas = self._plan.metadata()
        out_refs = []
        dropped = 0
        from ray_tpu.data.plan import _slice_concat

        for ref, meta in zip(refs, metas):
            rows = meta.num_rows
            if dropped >= n:
                out_refs.append(ref)
            elif dropped + rows <= n:
                dropped += rows
            else:
                take = n - dropped
                out_refs.append(_slice_concat.remote(
                    [(0, take, rows)], ref))
                dropped = n
        plan = ExecutionPlan([])
        plan._cached = out_refs
        return Dataset(plan)

    def window(self, *, blocks_per_window: int = 10):
        """Stream execution one window of blocks at a time
        (reference `Dataset.window` → DatasetPipeline): memory is
        bounded to a window instead of the whole dataset."""
        from ray_tpu.data.pipeline import DatasetPipeline

        return DatasetPipeline.from_dataset(self, blocks_per_window)

    def repeat(self, epochs: int):
        """Re-stream the dataset `epochs` times (reference
        `Dataset.repeat` → DatasetPipeline for multi-epoch training)."""
        from ray_tpu.data.pipeline import DatasetPipeline

        return DatasetPipeline.from_repeated(self, epochs)

    def train_test_split(self, test_size: float, *,
                         shuffle: bool = False,
                         seed: Optional[int] = None):
        ds = self.random_shuffle(seed=seed) if shuffle else self
        n = ds.count()
        n_test = int(n * test_size) if isinstance(test_size, float) \
            else test_size
        parts = ds.split_at_indices([n - n_test])
        return parts[0], parts[1]

    # ------------------------------------------------------------------
    # Consumption
    # ------------------------------------------------------------------

    def count(self) -> int:
        return sum(m.num_rows or 0 for m in self._plan.metadata())

    def num_blocks(self) -> int:
        return len(self._plan.execute())

    def size_bytes(self) -> int:
        return sum(m.size_bytes or 0 for m in self._plan.metadata())

    def schema(self):
        for m in self._plan.metadata():
            if m.schema is not None:
                return m.schema
        return None

    def input_files(self) -> List[str]:
        out: List[str] = []
        for m in self._plan.metadata():
            out.extend(m.input_files)
        return out

    def get_internal_block_refs(self) -> List:
        return self._plan.execute()

    def materialize(self) -> "Dataset":
        self._plan.execute()
        return self

    def take(self, limit: int = 20) -> List[Any]:
        out: List[Any] = []
        for ref in self._plan.iter_block_refs():
            block = ray_tpu.get(ref)
            for row in BlockAccessor(block).iter_rows():
                out.append(row)
                if len(out) >= limit:
                    return out
        return out

    def take_all(self) -> List[Any]:
        return self.take(limit=int(1e18))

    def show(self, limit: int = 20) -> None:
        for row in self.take(limit):
            print(row)

    def iter_rows(self) -> Iterator[Any]:
        for ref in self._plan.iter_block_refs():
            yield from BlockAccessor(ray_tpu.get(ref)).iter_rows()

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "default",
                     prefetch_batches: int = 1,
                     drop_last: bool = False) -> Iterator[Any]:
        from ray_tpu.data.iterator import iter_batches_from_refs

        return iter_batches_from_refs(
            self._plan.iter_block_refs(window=max(2, prefetch_batches + 1)),
            batch_size=batch_size, batch_format=batch_format,
            drop_last=drop_last, prefetch=prefetch_batches)

    def iter_jax_batches(self, *, batch_size: Optional[int] = 256,
                         dtypes: Optional[dict] = None,
                         device=None, sharding=None,
                         prefetch_batches: int = 2,
                         drop_last: bool = True) -> Iterator[Any]:
        """TPU ingest: numpy batches staged onto device (or a sharding)
        with double-buffering. The analog of `iter_torch_batches`
        (reference `data/dataset_iterator.py:143`)."""
        from ray_tpu.data.iterator import iter_device_batches

        return iter_device_batches(
            self._plan.iter_block_refs(window=max(2, prefetch_batches + 1)),
            batch_size=batch_size, dtypes=dtypes, device=device,
            sharding=sharding, prefetch=prefetch_batches,
            drop_last=drop_last)

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           dtypes: Optional[dict] = None,
                           device: Optional[str] = None,
                           prefetch_batches: int = 1,
                           drop_last: bool = False) -> Iterator[Any]:
        """Batches as torch tensors (reference
        `data/dataset_iterator.py:143` iter_torch_batches); CPU torch in
        this image, `device=` passes through to `.to()`."""
        import numpy as np
        import torch

        for batch in self.iter_batches(
                batch_size=batch_size, batch_format="numpy",
                prefetch_batches=prefetch_batches, drop_last=drop_last):
            def to_tensor(v, col=None):
                arr = np.asarray(v)
                if not arr.flags.writeable:
                    arr = arr.copy()  # arrow-backed views are read-only
                t = torch.as_tensor(arr)
                # dtypes: a single torch.dtype for every column/array,
                # or {column: dtype} for dict batches.
                if isinstance(dtypes, dict):
                    if col is not None and col in dtypes:
                        t = t.to(dtypes[col])
                elif dtypes is not None:
                    t = t.to(dtypes)
                if device:
                    t = t.to(device)
                return t

            if isinstance(batch, dict):
                yield {k: to_tensor(v, k) for k, v in batch.items()}
            else:
                yield to_tensor(batch)

    def to_pandas(self, limit: Optional[int] = None):
        import pandas as pd

        refs = self._plan.execute()
        dfs = [BlockAccessor(b).to_pandas()
               for b in ray_tpu.get(list(refs))]
        df = pd.concat(dfs, ignore_index=True) if dfs else pd.DataFrame()
        return df.head(limit) if limit else df

    def to_arrow_refs(self) -> List:
        return self._plan.execute()

    def to_numpy(self, column: Optional[str] = None):
        refs = self._plan.execute()
        batches = [BlockAccessor(b).to_numpy(column)
                   for b in ray_tpu.get(list(refs))]
        if column is not None:
            return np.concatenate(batches) if batches else np.array([])
        keys = batches[0].keys() if batches else []
        return {k: np.concatenate([b[k] for b in batches]) for k in keys}

    # -- aggregates ------------------------------------------------------

    def sum(self, on: str):
        return self._agg_column(on, np.sum)

    def min(self, on: str):
        return self._agg_column(on, np.min)

    def max(self, on: str):
        return self._agg_column(on, np.max)

    def mean(self, on: str):
        total = self._agg_column(on, np.sum)
        return total / max(self.count(), 1)

    def std(self, on: str):
        vals = self.to_numpy(on)
        return float(np.std(vals, ddof=1)) if len(vals) > 1 else 0.0

    def _agg_column(self, on: str, fn):
        @ray_tpu.remote
        def _agg(block):
            arr = BlockAccessor(block).to_numpy(on)
            return fn(arr) if len(arr) else None

        parts = [p for p in ray_tpu.get(
            [_agg.remote(r) for r in self._plan.execute()])
            if p is not None]
        return fn(np.asarray(parts)) if parts else None

    # ------------------------------------------------------------------
    # Writes
    # ------------------------------------------------------------------

    def write_parquet(self, path: str) -> List[str]:
        return self._write(ds_mod.write_block_parquet, path)

    def write_csv(self, path: str) -> List[str]:
        return self._write(ds_mod.write_block_csv, path)

    def write_json(self, path: str) -> List[str]:
        return self._write(ds_mod.write_block_json, path)

    def write_numpy(self, path: str, *, column: str = "data") -> List[str]:
        import os

        @ray_tpu.remote
        def _write(block, i):
            os.makedirs(path, exist_ok=True)
            out = os.path.join(path, f"part-{i:06d}.npy")
            np.save(out, BlockAccessor(block).to_numpy(column))
            return out

        return ray_tpu.get([_write.remote(r, i)
                            for i, r in enumerate(self._plan.execute())])

    def _write(self, writer, path: str) -> List[str]:
        @ray_tpu.remote
        def _w(block, i):
            return writer(block, path, i)

        return ray_tpu.get([_w.remote(r, i)
                            for i, r in enumerate(self._plan.execute())])

    # ------------------------------------------------------------------

    def stats(self) -> str:
        import json

        self._plan.execute()
        return json.dumps([s.summary() for s in self._plan.stats])

    def __repr__(self) -> str:
        try:
            nb = len(self._plan._cached) if self._plan._cached else "?"
        except Exception:
            nb = "?"
        return f"Dataset(num_blocks={nb}, ops={len(self._plan.ops)})"


# ---------------------------------------------------------------------------
# Creation API (module-level, re-exported from ray_tpu.data)
# ---------------------------------------------------------------------------


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    return Dataset(ExecutionPlan([Read(
        name="ReadRange", datasource=ds_mod.RangeDatasource(n),
        parallelism=parallelism)]))


def range_tensor(n: int, *, shape: tuple = (1,),
                 parallelism: int = 8) -> Dataset:
    return Dataset(ExecutionPlan([Read(
        name="ReadRangeTensor",
        datasource=ds_mod.RangeDatasource(n, tensor_shape=shape),
        parallelism=parallelism)]))


def from_items(items: List[Any], *, parallelism: int = 8) -> Dataset:
    return Dataset(ExecutionPlan([Read(
        name="FromItems", datasource=ds_mod.ItemsDatasource(items),
        parallelism=parallelism)]))


def from_pandas(dfs) -> Dataset:
    if not isinstance(dfs, list):
        dfs = [dfs]
    return Dataset(ExecutionPlan([FromBlocks(name="FromPandas",
                                             blocks=list(dfs))]))


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return Dataset(ExecutionPlan([FromBlocks(name="FromArrow",
                                             blocks=list(tables))]))


def from_numpy(arrays, column: str = "data") -> Dataset:
    if not isinstance(arrays, list):
        arrays = [arrays]
    blocks = [BlockAccessor.batch_to_block({column: a}) for a in arrays]
    return Dataset(ExecutionPlan([FromBlocks(name="FromNumpy",
                                             blocks=blocks)]))


def read_parquet(paths, *, columns: Optional[List[str]] = None,
                 parallelism: int = -1) -> Dataset:
    return Dataset(ExecutionPlan([Read(
        name="ReadParquet",
        datasource=ds_mod.ParquetDatasource(paths, columns=columns),
        parallelism=parallelism)]))


def read_csv(paths, *, parallelism: int = -1, **opts) -> Dataset:
    return Dataset(ExecutionPlan([Read(
        name="ReadCSV", datasource=ds_mod.CSVDatasource(paths, **opts),
        parallelism=parallelism)]))


def read_json(paths, *, parallelism: int = -1, **opts) -> Dataset:
    return Dataset(ExecutionPlan([Read(
        name="ReadJSON", datasource=ds_mod.JSONDatasource(paths, **opts),
        parallelism=parallelism)]))


def read_numpy(paths, *, parallelism: int = -1) -> Dataset:
    return Dataset(ExecutionPlan([Read(
        name="ReadNumpy", datasource=ds_mod.NumpyDatasource(paths),
        parallelism=parallelism)]))


def read_binary_files(paths, *, parallelism: int = -1) -> Dataset:
    return Dataset(ExecutionPlan([Read(
        name="ReadBinary", datasource=ds_mod.BinaryDatasource(paths),
        parallelism=parallelism)]))


def read_text(paths, *, parallelism: int = -1) -> Dataset:
    return Dataset(ExecutionPlan([Read(
        name="ReadText", datasource=ds_mod.TextDatasource(paths),
        parallelism=parallelism)]))


def read_images(paths, *, parallelism: int = -1, **opts) -> Dataset:
    return Dataset(ExecutionPlan([Read(
        name="ReadImages", datasource=ds_mod.ImageDatasource(paths,
                                                             **opts),
        parallelism=parallelism)]))


def read_tfrecords(paths, *, parallelism: int = -1, **opts) -> Dataset:
    return Dataset(ExecutionPlan([Read(
        name="ReadTFRecords",
        datasource=ds_mod.TFRecordDatasource(paths, **opts),
        parallelism=parallelism)]))


def read_webdataset(paths, *, parallelism: int = -1, **opts) -> Dataset:
    """Tar shards in the WebDataset sample convention (reference
    `data/read_api.py` read_webdataset)."""
    return Dataset(ExecutionPlan([Read(
        name="ReadWebDataset",
        datasource=ds_mod.WebDatasetDatasource(paths, **opts),
        parallelism=parallelism)]))


def read_sql(sql: str, connection_factory, *,
             queries: Optional[List[str]] = None,
             parallelism: int = -1) -> Dataset:
    """DBAPI-2 query read (reference `data/read_api.py` read_sql);
    ``queries`` gives caller-partitioned parallel reads."""
    return Dataset(ExecutionPlan([Read(
        name="ReadSQL",
        datasource=ds_mod.SQLDatasource(sql, connection_factory,
                                        queries=queries),
        parallelism=parallelism)]))


def read_datasource(datasource: ds_mod.Datasource, *,
                    parallelism: int = -1) -> Dataset:
    return Dataset(ExecutionPlan([Read(
        name="ReadCustom", datasource=datasource,
        parallelism=parallelism)]))
