"""Exception types surfaced by the public API.

Parallels ``python/ray/exceptions.py`` in the reference: user-code failures
are captured where they happen, stored as the value of the task's return
objects, and re-raised at every ``get`` with the remote traceback attached.
"""

from __future__ import annotations

import traceback as _traceback


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task application raised an exception.

    The remote traceback is captured as text and appended to the message so
    it survives serialization across process boundaries (reference:
    ``RayTaskError`` in ``python/ray/exceptions.py``).
    """

    def __init__(self, cause: BaseException, task_desc: str = "",
                 remote_traceback: str | None = None):
        self.cause = cause
        self.task_desc = task_desc
        if remote_traceback is None:
            remote_traceback = "".join(
                _traceback.format_exception(type(cause), cause, cause.__traceback__)
            )
        self.remote_traceback = remote_traceback
        super().__init__(
            f"task {task_desc} failed: {type(cause).__name__}: {cause}\n"
            f"--- remote traceback ---\n{remote_traceback}"
        )

    def __reduce__(self):
        # Default exception pickling reconstructs from self.args (the
        # formatted message), which would arrive as a str `cause`.
        return (TaskError,
                (self.cause, self.task_desc, self.remote_traceback))

    def as_instanceof_cause(self) -> BaseException:
        """Return an exception that is an instance of the cause's class.

        Lets ``except UserError`` work at the ``get`` site while preserving
        the remote traceback, like the reference's dual-inheritance trick.
        """
        cause_cls = type(self.cause)
        if cause_cls is TaskError:
            return self
        try:
            class _Wrapped(TaskError, cause_cls):  # type: ignore[misc, valid-type]
                def __init__(self, te: TaskError):
                    TaskError.__init__(
                        self, te.cause, te.task_desc, te.remote_traceback
                    )

                def __reduce__(self):
                    # The dynamic dual-inheritance class doesn't survive
                    # pickling as-is (exceptions reconstruct from
                    # self.args — the message string). Rebuild from a
                    # plain TaskError and re-wrap on the other side.
                    return (_rebuild_wrapped_task_error,
                            (TaskError(self.cause, self.task_desc,
                                       self.remote_traceback),))

            _Wrapped.__name__ = f"TaskError({cause_cls.__name__})"
            _Wrapped.__qualname__ = _Wrapped.__name__
            return _Wrapped(self)
        except TypeError:
            return self


def _rebuild_wrapped_task_error(te: "TaskError") -> BaseException:
    return te.as_instanceof_cause()


class ActorError(RayTpuError):
    """An actor task cannot run because the actor is dead or unreachable."""


class ActorDiedError(ActorError):
    def __init__(self, actor_desc: str = "", cause: str = ""):
        super().__init__(f"actor {actor_desc} died: {cause}")
        self.actor_desc = actor_desc


class ActorUnavailableError(ActorError):
    """Actor temporarily unreachable (restarting)."""


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class ObjectLostError(RayTpuError):
    """Object's value was lost (evicted / node died) and cannot be recovered."""

    def __init__(self, object_id_hex: str = "", msg: str = ""):
        super().__init__(msg or f"object {object_id_hex} was lost")
        self.object_id_hex = object_id_hex


class OwnerDiedError(ObjectLostError):
    pass


class ObjectStoreFullError(RayTpuError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get`` did not complete within the requested timeout."""


class TaskCancelledError(RayTpuError):
    def __init__(self, task_desc: str = ""):
        super().__init__(f"task {task_desc} was cancelled")


class RuntimeEnvSetupError(RayTpuError):
    pass


class PlacementGroupSchedulingError(RayTpuError):
    pass


class PendingCallsLimitExceeded(RayTpuError):
    pass


class JobQuotaExceededError(RayTpuError):
    """The submitting job is over a configured tenancy quota (the
    queued-task ceiling): the submission was rejected at admission,
    before consuming any cluster capacity. The message names the job,
    the exhausted quota, and the config knob (``job_quotas``)."""

    def __init__(self, job_id: str = "", msg: str = ""):
        super().__init__(msg or f"job {job_id!r} exceeded its quota")
        self.job_id = job_id
